"""Pre-framed task-spec templates + function push-through ledger.

Submission-plane analog of the reference's cached ``TaskSpec`` protos
(``common/task/task_spec.h`` — the immutable spec is built once per
function/options pair and reused across submissions): the invariant
portion of a ``push_task`` header (owner address, task name, runtime env,
retry budget) is serialized to ONE msgpack blob per (function, options)
on the submitting worker and spliced into every wire message as an opaque
frame. The pump-thread hot path then packs a 4-key per-call delta header
(task id, function key, return count, spec flag) instead of re-framing
the full spec for every task in a burst; the executing side decodes each
distinct spec blob once through :class:`SpecCache`.

:class:`FnPushLedger` is the second half of the submission cache: the
exporter keeps the cloudpickle blob of every function it has exported
(or loaded) and piggybacks it on the FIRST ``push_task`` carrying that
fkey to each peer (wire flag ``fb``), so a fresh worker installs the
function from the push itself instead of issuing a ``gcs.kv_get`` — the
function table becomes a fallback, not a hot path (reference: function
table pushes ride the same channel as task specs in
``core_worker/transport``).

Round 16 adds :class:`PushWindow` — the transit-pacing sibling: instead
of a fixed per-slot fan-out (16 pushers x 16-task chunks = up to 256
tasks parked between the driver's pending queue and the executor pool),
each leased slot carries an AIMD congestion window clocked by observed
chunk-settle latency, so a saturated executor stops accumulating parked
chunks and an idle one ramps immediately (reference: the transport-level
send-window discipline in TCP congestion control, applied to the task
wire; the reference core worker bounds in-flight PushNormalTask per
lease the same way its client streams bound outstanding requests).

Round 15 adds the REPLY-side siblings, so result delivery amortizes the
way submission does (reference: the core worker's reply path batches
task results onto the submission channel; plasma inline-object returns):

- :class:`ReplyWindow` coalesces small execution results from one peer
  connection into a single multi-result frame with the same self-clocking
  discipline as ``create_actor_batch``: the first result flushes
  immediately, everything completing while that frame's ack is in flight
  rides the next frame — O(bursts) reply messages for a queued burst,
  and chunk-mates never serialize behind each other's acks.
- :class:`ArgLedger` is the FnPushLedger discipline applied to argument
  bytes: a repeated small argument frame (the "same config dict to 10k
  tasks" shape) is content-hashed at push time and shipped ONCE per
  (peer, digest); subsequent pushes carry only the digest.
- :class:`ArgInternCache` is the executing side's bounded LRU for those
  interned frames; an evicted digest surfaces as a typed miss the pusher
  answers by re-sending the exact bytes.

Round 20 adds the driver's loop scale-out primitives (reference: the
core worker runs on a dedicated asio loop with per-connection strands;
here the single Python event loop splits into cooperating planes):

- :class:`PlaneQueue` is the bounded cross-thread handoff all planes
  share: producers ``offer()`` items from any thread, one dedicated
  worker thread drains the queue in whole batches, and a full queue
  rejects the offer so the producer degrades to its inline on-loop
  path — backpressure never loses work.
- :class:`SettlePlane` rides a PlaneQueue to move TCP reply settling
  off the event loop: the recv loop hands whole coalesced reply frames
  over; the plane thread splits/decodes them and re-enters each target
  event loop with ONE ``call_soon_threadsafe`` per drain per loop
  (grouping by the future's owning loop is what lets sharded pusher
  loops settle correctly too). The ring pump never queues here — it
  already runs off-loop, so attachment just switches it to prepare
  each drain's replies in place on the pump thread under the same
  per-loop-bucketed discipline.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import msgpack

logger = logging.getLogger(__name__)

# Keys a spec template may carry; everything else in a push_task header is
# a per-call delta (tid, fkey, nret, argrefs, borrows, trace, corr ids).
SPEC_KEYS = ("owner", "name", "renv", "retries")


def pack_spec(spec: dict) -> bytes:
    """Serialize the invariant spec fields once (template build time)."""
    return msgpack.packb(spec, use_bin_type=True)


class SpecCache:
    """Receiver-side spec decode cache: spec bytes -> header-fragment dict.

    A burst of K tasks of one function ships K identical spec frames but
    costs ONE unpack here (bytes hash once, then dict hits). Bounded: at
    capacity the oldest half is dropped (specs are tiny and re-decodable,
    so eviction only costs a future unpack). The returned dict is shared —
    callers must merge-copy (``{**spec, **h}``), never mutate.
    """

    def __init__(self, cap: int = 1024):
        self._cap = max(int(cap), 2)
        self._decoded: Dict[bytes, dict] = {}

    def get(self, blob: bytes) -> dict:
        d = self._decoded.get(blob)
        if d is None:
            d = msgpack.unpackb(blob, raw=False)
            if len(self._decoded) >= self._cap:
                # pop, not del: the ring fast path (pump thread) and the
                # loop slow path may evict concurrently
                for k in list(self._decoded)[: self._cap // 2]:
                    self._decoded.pop(k, None)
            self._decoded[blob] = d
        return d


class FnPushLedger:
    """Function-blob push-through bookkeeping on the SUBMITTING side.

    ``store`` keeps the pickled function bytes at export/load time;
    ``blob_for`` returns the blob exactly once per (peer, fkey) — the
    caller attaches it to that push and the peer installs it into its
    function cache. A peer that never receives the blob (batch fallback,
    connection churn) still resolves through the head KV, so this ledger
    only ever removes RPCs, never correctness.

    Thread-safe: the slot pushers run on the core loop but export/load
    can happen from caller threads.
    """

    def __init__(self, cap: int = 256):
        self._cap = max(int(cap), 2)
        self._blobs: Dict[str, bytes] = {}
        self._sent: Dict[Tuple, Set[str]] = {}
        self._lock = threading.Lock()

    def store(self, fkey: str, blob: bytes):
        with self._lock:
            if fkey in self._blobs:
                return
            if len(self._blobs) >= self._cap:
                for k in list(self._blobs)[: self._cap // 2]:
                    del self._blobs[k]
            self._blobs[fkey] = blob

    def blob_for(self, peer, fkey: str) -> Optional[bytes]:
        """The blob to piggyback on this push, or None (already sent to
        this peer, or blob unknown). Marks the peer as covered only when
        a blob is actually returned."""
        with self._lock:
            sent = self._sent.get(peer)
            if sent is not None and fkey in sent:
                return None
            blob = self._blobs.get(fkey)
            if blob is None:
                return None
            if sent is None:
                sent = self._sent[peer] = set()
            sent.add(fkey)
            return blob

    def forget_peer(self, peer):
        """Peer connection torn down: a successor process at the same
        address must be re-covered (it lost its function cache)."""
        with self._lock:
            self._sent.pop(peer, None)


class ReplyWindow:
    """Self-clocking coalescer for executor-side result replies.

    Reply-plane sibling of the ``create_actor_batch`` client window: the
    first result added to an idle window flushes immediately (a
    multi-result frame of one — latency is never traded away when the
    path is quiet), and every result completing while that frame's ack is
    still in flight buffers and rides the NEXT frame, flushed by
    :meth:`on_ack` when the receiving pump acknowledges (``mrack``). A
    queued burst therefore costs O(bursts) reply messages instead of one
    per task, and a fast chunk-mate's result is never parked behind a
    whole executor queue drain.

    Bounds: ``max_items``/``max_bytes`` force a flush mid-window (memory
    and transport-frame caps win over coalescing), and ``horizon_s``
    re-arms a window whose ack was lost — a dropped frame degrades to
    per-deadline replay at the pusher, never a wedged window.

    Thread-safe: results arrive from executor threads, acks from the
    transport pump or the event loop. ``send(items)`` runs OUTSIDE the
    lock with ``items`` = [(sub_header, frames, tag)]; the caller owns
    transport errors (a peer that vanished mid-flush loses the frame the
    same way it loses any reply — its pusher's deadline recovers).
    """

    def __init__(self, send: Callable[[List[tuple]], None],
                 max_items: int = 128, max_bytes: int = 256 * 1024,
                 horizon_s: float = 1.0, gap_s: Optional[float] = None,
                 defer: Optional[Callable[[float, Callable], None]] = None):
        self._send = send
        self._max_items = max(int(max_items), 1)
        self._max_bytes = max(int(max_bytes), 1)
        self._horizon_s = float(horizon_s)
        # Clock mode. Ack (gap_s None): flushes ride the peer's ``mrack``
        # — right for TCP, where reply rates are low and the ack is one
        # more asyncio write. Timer (gap_s + defer): flushes are paced by
        # a minimum gap with a deferred tail flush — right for the shm
        # ring, where per-flush mracks measurably contend with the
        # pusher's sends on the ring's send lock (profiled on a 1-core
        # A/B box: the ack traffic alone cost ~5% of queued throughput).
        self._gap_s = None if gap_s is None else float(gap_s)
        self._defer = defer
        self._timer_armed = False
        self._lock = threading.Lock()
        self._buf: List[tuple] = []
        self._buf_bytes = 0
        self._inflight = False
        self._inflight_t = 0.0
        self.flushes = 0
        self.coalesced = 0

    def add(self, sub: dict, frames: List[bytes], tag: Any = None):
        self.add_many(((sub, frames, tag),))

    def add_many(self, items) -> None:
        """Insert one or many results under ONE lock (and at most one
        emit): an executor drain loop hands over its micro-batch every
        few completions/ms, so per-result window cost stays off the task
        hot path while the flush semantics (immediate first flush,
        ack/gap riding, caps, horizon re-arm) are identical."""
        if not items:
            return
        nbytes = 0
        for _s, frames, _t in items:
            for f in frames:
                nbytes += len(f)
        now = time.monotonic()
        fire = None
        with self._lock:
            if self._gap_s is not None:
                gap_left = self._gap_s - (now - self._inflight_t)
                if gap_left <= 0:
                    # Quiet window: this batch goes out now (with any
                    # stragglers a timer hasn't picked up yet).
                    batch = self._buf + list(items)
                    self._buf, self._buf_bytes = [], 0
                    self._inflight_t = now
                else:
                    self._buf.extend(items)
                    self._buf_bytes += nbytes
                    if (len(self._buf) < self._max_items
                            and self._buf_bytes < self._max_bytes):
                        if not self._timer_armed:
                            # Tail guarantee: if no later add crosses the
                            # gap, the deferred callback flushes what
                            # buffered here.
                            self._timer_armed = True
                            fire = gap_left
                        batch = None
                    else:
                        batch, self._buf, self._buf_bytes = self._buf, [], 0
                        self._inflight_t = now
            elif (self._inflight
                    and (now - self._inflight_t) < self._horizon_s):
                self._buf.extend(items)
                self._buf_bytes += nbytes
                if (len(self._buf) < self._max_items
                        and self._buf_bytes < self._max_bytes):
                    batch = None  # rides the in-flight frame's ack
                else:
                    batch, self._buf, self._buf_bytes = self._buf, [], 0
                    self._inflight = True
                    self._inflight_t = now
            else:
                # Idle window (or the ack horizon lapsed — lost ack):
                # whatever accumulated goes out WITH this result, now.
                batch = self._buf + list(items)
                self._buf, self._buf_bytes = [], 0
                self._inflight = True
                self._inflight_t = now
        if fire is not None and self._defer is not None:
            self._defer(fire, self._flush_timer)
        if batch:
            self._emit(batch)

    def _flush_timer(self):
        """Deferred tail flush (timer mode): whatever buffered inside the
        gap goes out even if no further result ever arrives. While
        results keep flowing the timer re-arms itself — it runs on the
        receiver loop where ``call_later`` is a heap push, so the
        steady-state clock costs no cross-thread wakeups (arming from an
        executor thread pays one; that now happens only on an
        idle->busy transition)."""
        with self._lock:
            if not self._buf:
                self._timer_armed = False  # quiesced: next add re-arms
                return
            batch, self._buf, self._buf_bytes = self._buf, [], 0
            self._inflight_t = time.monotonic()
        self._emit(batch)
        if self._defer is not None:
            self._defer(self._gap_s, self._flush_timer)

    def on_ack(self):
        """The peer acknowledged the in-flight frame: flush what
        accumulated behind it, or go idle. No-op in timer mode (the gap
        clock paces flushes; there are no acks to ride)."""
        if self._gap_s is not None:
            return
        with self._lock:
            if not self._buf:
                self._inflight = False
                return
            batch, self._buf, self._buf_bytes = self._buf, [], 0
            self._inflight_t = time.monotonic()  # window stays clocked
        self._emit(batch)

    def flush(self):
        """Unconditional drain (shutdown / graceful node drain): buffered
        results must never die with the window."""
        with self._lock:
            if not self._buf:
                return
            batch, self._buf, self._buf_bytes = self._buf, [], 0
            self._inflight = True
            self._inflight_t = time.monotonic()
        self._emit(batch)

    def _emit(self, batch: List[tuple]):
        self.flushes += 1
        self.coalesced += len(batch)
        self._send(batch)


class PushWindow:
    """Adaptive in-flight push window for one leased slot (AIMD).

    Units are TASKS in flight between the driver's pending queue and the
    executor pool: a pusher asks :meth:`grant` for chunk capacity before
    packing, and reports each chunk's settle via :meth:`on_settled` with
    the observed push->reply latency. The window then self-clocks:

    - **additive grow** on a clean drain (+1 task per settled chunk, up
      to ``ceiling``) — an idle executor's settles come back fast and
      often, so it ramps immediately;
    - **multiplicative shrink** (x ``beta``, floored at ``floor``) when
      settle latency inflates past ``latency_factor`` x the tracked
      clean baseline — chunks are queueing in ring transit or the
      executor pool, and parking more behind them only grows the queue.

    The baseline tracks the MINIMUM observed settle latency with a slow
    upward drift, so a durable latency regime change (the workload
    itself got slower) re-baselines instead of shrinking forever;
    ``min_base_s`` keeps micro-latency noise on a quiet box from reading
    as 3x inflation.

    Pure state + arithmetic on the caller's thread (the driver's event
    loop): no locks, no clocks of its own — the caller supplies latency
    measurements, which keeps the class unit-testable with synthetic
    inflation exactly like :class:`ReplyWindow`'s synthetic acks.
    """

    __slots__ = ("floor", "ceiling", "_win", "_factor", "_beta",
                 "_min_base_s", "_base_s", "inflight", "peak",
                 "grows", "shrinks", "settled")

    def __init__(self, initial: int = 64, floor: int = 4,
                 ceiling: int = 256, latency_factor: float = 6.0,
                 beta: float = 0.5, min_base_s: float = 0.002):
        self.floor = max(int(floor), 1)
        self.ceiling = max(int(ceiling), self.floor)
        self._win = float(min(max(int(initial), self.floor), self.ceiling))
        self._factor = float(latency_factor)
        self._beta = float(beta)
        self._min_base_s = float(min_base_s)
        self._base_s: Optional[float] = None
        self.inflight = 0
        self.peak = int(self._win)
        self.grows = 0
        self.shrinks = 0
        self.settled = 0

    @property
    def window(self) -> int:
        return int(self._win)

    def grant(self, want: int, min_grant: int = 1) -> int:
        """How many of ``want`` tasks may enter flight now (0 = not
        enough room; the caller waits for a sibling chunk to settle).
        ``min_grant`` sets the smallest acceptable grant — pushers pass
        half a chunk so a nearly-full window parks them instead of
        fragmenting the burst into 1-2 task wire messages."""
        room = int(self._win) - self.inflight
        n = min(int(want), room)
        if n < max(int(min_grant), 1):
            return 0
        self.inflight += n
        return n

    def release(self, n: int):
        """Return unused/failed grant capacity without a pacing signal
        (chunk packed smaller than granted, transport error paths)."""
        if n > 0:
            self.inflight = max(self.inflight - n, 0)

    def on_settled(self, n: int, latency_s: float) -> bool:
        """``n`` tasks settled after ``latency_s``: release their flight
        slots and update the window. Returns True for a clean drain
        (grew), False for an inflation shrink."""
        self.inflight = max(self.inflight - n, 0)
        if n <= 0:
            return True
        self.settled += n
        base = self._base_s
        if base is None:
            self._base_s = max(latency_s, 0.0)
            return True
        if latency_s < base:
            self._base_s = latency_s
        else:
            # Slow upward drift: ~50 settles to absorb a durable change.
            self._base_s = base + 0.02 * (latency_s - base)
        if latency_s > self._factor * max(base, self._min_base_s):
            self._win = max(self._win * self._beta, float(self.floor))
            self.shrinks += 1
            return False
        self._win = min(self._win + 1.0, float(self.ceiling))
        self.grows += 1
        if int(self._win) > self.peak:
            self.peak = int(self._win)
        return True

    def reset(self):
        """Cold re-ramp (chaos ``drop`` kind, slot loss): pacing state is
        gone; capacity accounting for in-flight chunks is kept — their
        settles still release correctly."""
        self._win = float(self.floor)
        self._base_s = None

    def snapshot(self) -> dict:
        return {
            "window": int(self._win), "inflight": self.inflight,
            "peak": self.peak, "grows": self.grows,
            "shrinks": self.shrinks, "settled": self.settled,
        }


class ArgLedger:
    """Sender-side (peer, digest) coverage for interned argument frames —
    the :class:`FnPushLedger` discipline applied to argument bytes. The
    first push carrying a digest to a peer ships the blob (wire key
    ``aib``) and marks coverage; later pushes carry only the digest
    (``ai``). Coverage is bounded per peer (oldest digests lapse — the
    blob is simply re-sent) and reset wholesale on slot loss, because a
    successor process at the same address starts with an empty cache.

    Thread-safe: slot pushers run on the core loop, but retry paths may
    reset coverage from other coroutines interleaved with them."""

    def __init__(self, per_peer_cap: int = 4096):
        self._cap = max(int(per_peer_cap), 2)
        self._sent: Dict[Any, "OrderedDict[bytes, None]"] = {}
        self._lock = threading.Lock()

    def covered(self, peer, digest: bytes) -> bool:
        """True when this peer already holds the blob for ``digest``.
        Otherwise marks it covered — the caller ships the blob on THIS
        push — and returns False."""
        with self._lock:
            sent = self._sent.get(peer)
            if sent is None:
                sent = self._sent[peer] = OrderedDict()
            if digest in sent:
                sent.move_to_end(digest)
                return True
            if len(sent) >= self._cap:
                sent.popitem(last=False)
            sent[digest] = None
            return False

    def forget_peer(self, peer):
        """Slot lost / typed intern miss: assume the peer's cache is gone
        and re-cover it from scratch (blobs re-sent, never correctness)."""
        with self._lock:
            self._sent.pop(peer, None)


class ArgInternCache:
    """Executing-side store for interned argument frames: digest ->
    exact frame bytes, LRU-bounded by total bytes. A miss (eviction,
    process restart, injected loss) is never silent — the caller raises
    the typed ``arg_intern_miss`` error and the pusher re-sends the
    blob, so the bytes that reach ``deserialize_frames`` are always
    byte-identical to what the submitter framed.

    Thread-safe: the ring pump expands fast-path headers while the event
    loop expands slow-path ones."""

    def __init__(self, cap_bytes: int = 64 << 20):
        self._cap = max(int(cap_bytes), 1)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[bytes, bytes]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    def put(self, digest: bytes, blob: bytes):
        with self._lock:
            old = self._entries.pop(digest, None)
            if old is not None:
                self._bytes -= len(old)
            self._entries[digest] = blob
            self._bytes += len(blob)
            while self._bytes > self._cap and self._entries:
                _, evicted = self._entries.popitem(last=False)
                self._bytes -= len(evicted)

    def get(self, digest: bytes) -> Optional[bytes]:
        with self._lock:
            blob = self._entries.get(digest)
            if blob is None:
                self.misses += 1
                return None
            self._entries.move_to_end(digest)
            self.hits += 1
            return blob

    def purge(self, digests):
        """Drop specific digests (the chaos ``drop`` kind simulates an
        eviction exactly where a lookup was about to hit)."""
        with self._lock:
            for d in digests:
                blob = self._entries.pop(d, None)
                if blob is not None:
                    self._bytes -= len(blob)


# --------------------------------------------------------------------------
# Round 20: driver loop scale-out planes.


def _apply_plane_ops(ops):
    """Loop-side applier for a settle-plane drain: one scheduled call
    runs every (fn, payload) op the plane bucketed for this loop. A
    single op failing must not strand the rest of the batch — each op's
    futures belong to a different connection/task set."""
    for fn, data in ops:
        try:
            fn(data)
        except Exception:
            logger.exception("settle-plane apply failed")


class PlaneQueue:
    """Bounded cross-thread handoff queue with a dedicated drain thread.

    The shared primitive under the round-20 driver planes: producers
    (the TCP recv loop, ring pump threads, submitting caller threads)
    ``offer()`` items; ONE worker thread wakes per burst, swaps out the
    whole backlog, and hands it to ``worker`` as a single batch — the
    economics every plane wants (O(drains) downstream wakeups, never
    O(items)).

    Backpressure is rejection, not blocking: a full queue makes
    ``offer()`` return False and the producer falls back to its inline
    on-loop path. The plane is an optimization — it must never be able
    to wedge or lose the hot path, so nothing here waits on the
    consumer. ``close()`` drains what is queued, then joins the thread
    (drivers create planes per process; tests create many workers and
    must not leak threads).
    """

    def __init__(self, name: str, worker: Callable[[list], None],
                 maxsize: int = 1024):
        self._worker = worker
        self._dq: deque = deque()
        self._event = threading.Event()
        self._closed = False
        self.maxsize = int(maxsize)
        self.stats = {
            "handoffs": 0, "rejects": 0, "drains": 0, "items": 0,
            "max_drain": 0, "peak_depth": 0,
        }
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True)
        self._thread.start()

    def depth(self) -> int:
        return len(self._dq)

    def offer(self, item) -> bool:
        """Enqueue from any thread; False = full/closed (caller goes
        inline). deque.append is atomic under the GIL — the depth check
        is advisory (the bound may briefly overshoot by one item per
        racing producer, which is fine for a backpressure valve)."""
        if self._closed:
            return False
        dq = self._dq
        st = self.stats
        if len(dq) >= self.maxsize:
            st["rejects"] += 1
            return False
        dq.append(item)
        st["handoffs"] += 1
        d = len(dq)
        if d > st["peak_depth"]:
            st["peak_depth"] = d
        self._event.set()
        return True

    def _run(self):
        dq = self._dq
        ev = self._event
        st = self.stats
        while True:
            ev.wait()
            ev.clear()
            batch = []
            while dq:
                try:
                    batch.append(dq.popleft())
                except IndexError:
                    break
            if batch:
                st["drains"] += 1
                st["items"] += len(batch)
                if len(batch) > st["max_drain"]:
                    st["max_drain"] = len(batch)
                try:
                    self._worker(batch)
                except Exception:
                    logger.exception("plane %s drain failed",
                                     self._thread.name)
            # Re-check AFTER the drain: close() sets the event exactly
            # once, and a concurrent clear() above could otherwise eat
            # that wakeup and park this thread in wait() forever.
            if self._closed and not dq:
                return

    def close(self, timeout: float = 1.0):
        self._closed = True
        self._event.set()
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=timeout)

    def snapshot(self) -> dict:
        out = dict(self.stats)
        out["depth"] = self.depth()
        return out


class SettlePlane:
    """Round-20 settle plane: reply settling off the driver's event loop.

    Producers hand (owner, payload) pairs over — the TCP recv loop
    offers a whole coalesced reply frame. (The ring pump never offers:
    it is itself an off-loop thread, so it runs the SAME prepare/apply
    discipline in place — ``_settle_prepare`` on the pump thread — and
    queueing here would only add a second cross-thread hop to the reply
    path.) The plane thread asks each owner to PREPARE
    the payload off-loop (``owner._settle_prepare(payload)`` returns
    ``[(target_loop, apply_fn, data), ...]``: splitting multi-result
    frames, popping ring futures under their lock, building exception
    objects), buckets the prepared ops by target event loop, and
    re-enters each loop with ONE ``call_soon_threadsafe`` per drain —
    the call-counted O(drains) wakeup contract
    (``tests/test_driver_loops.py``). Grouping by the future's owning
    loop is load-bearing: with sharded pusher loops, one drain can
    carry futures homed on several loops.

    A full queue (or the ``driver.settle.handoff`` faultpoint) degrades
    the producer to the inline on-loop settle path — frames are never
    lost, only un-offloaded.
    """

    FAULT = "driver.settle.handoff"

    def __init__(self, maxsize: int = 1024):
        self.q = PlaneQueue("rt-settle", worker=self._drain_on_plane,
                            maxsize=maxsize)
        self.applies = 0  # call_soon_threadsafe count, O(drains x loops)

    def depth(self) -> int:
        return self.q.depth()

    def offer(self, owner, payload) -> bool:
        """True = the plane took the frame (producer must NOT settle
        inline). Fault injection degrades to inline: error/drop reject
        the offer, delay stalls the producer then proceeds."""
        from ray_tpu._private import faultpoints

        if faultpoints.ACTIVE:
            try:
                if faultpoints.fire("driver.settle.handoff") == "drop":
                    return False
            except Exception:
                return False
        return self.q.offer((owner, payload))

    def _drain_on_plane(self, batch):
        buckets: Dict[Any, list] = {}
        for owner, payload in batch:
            try:
                for loop, fn, data in owner._settle_prepare(payload):
                    buckets.setdefault(loop, []).append((fn, data))
            except Exception:
                logger.exception("settle-plane prepare failed")
        for loop, ops in buckets.items():
            try:
                loop.call_soon_threadsafe(_apply_plane_ops, ops)
                self.applies += 1
            except RuntimeError:
                # Loop already closed (shutdown); its futures were
                # failed by connection teardown.
                pass

    def close(self, timeout: float = 1.0):
        self.q.close(timeout=timeout)

    def snapshot(self) -> dict:
        out = self.q.snapshot()
        out["applies"] = self.applies
        return out
