"""Worker (node) process entrypoint.

TPU-native process-per-host model: one of these processes is one "node" —
on a real TPU pod it owns all local chips via jax; in tests many of them
simulate a cluster on one machine (reference analog: raylet + worker combined;
spawned like ``services.py start_raylet``).
"""
from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import signal
import sys


def _install_jax_platform_pin():
    """Re-assert JAX_PLATFORMS via jax.config the moment jax is imported.

    If jax is already loaded, pin now; otherwise install a meta-path hook
    that fires once after the real jax module executes.
    """
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return

    def pin(jax_mod):
        try:
            jax_mod.config.update("jax_platforms", plat)
        except Exception:
            pass

    if "jax" in sys.modules:
        pin(sys.modules["jax"])
        return

    import importlib.abc
    import importlib.machinery

    class _PinningLoader(importlib.abc.Loader):
        def __init__(self, inner):
            self._inner = inner

        def create_module(self, spec):
            return self._inner.create_module(spec)

        def exec_module(self, module):
            self._inner.exec_module(module)
            pin(module)
            try:
                sys.meta_path.remove(finder)
            except ValueError:
                pass

    class _Finder(importlib.abc.MetaPathFinder):
        def find_spec(self, name, path, target=None):
            if name != "jax":
                return None
            sys.meta_path.remove(finder)  # avoid recursion
            try:
                spec = importlib.util.find_spec(name)
            finally:
                sys.meta_path.insert(0, finder)
            if spec is None or spec.loader is None:
                return None
            spec.loader = _PinningLoader(spec.loader)
            return spec

    import importlib.util

    finder = _Finder()
    sys.meta_path.insert(0, finder)


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-host", required=True)
    parser.add_argument("--gcs-port", type=int, required=True)
    parser.add_argument("--resources", default="{}")
    parser.add_argument("--labels", default="{}")
    parser.add_argument("--job-id", required=True)
    parser.add_argument("--node-id", default="")
    parser.add_argument("--log-level", default="WARNING")
    # Warm worker pool member: registers with the head but stays out of
    # the scheduler until activated (gcs._activate_standby).
    parser.add_argument("--standby", action="store_true")
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=getattr(logging, args.log_level.upper(), logging.WARNING),
        format=f"[rt-worker {os.getpid()}] %(levelname)s %(name)s: %(message)s",
    )

    # SIGUSR1 → dump all thread stacks to stderr (debugging stuck workers;
    # reference analog: py-spy hooks in dashboard/modules/reporter).
    import faulthandler
    faulthandler.register(signal.SIGUSR1, all_threads=True)

    # Workers default to CPU jax unless the node was explicitly given TPUs:
    # only one process may own the TPU chips.
    resources = json.loads(args.resources)
    # Log plane: point fds 1/2 at session-dir files BEFORE anything prints
    # (reference behavior: workers write redirected log files that a
    # monitor tails — _private/log_monitor.py). Skipped when no session
    # dir rides the env (standalone/manual runs keep inherited stdio).
    log_paths = []
    session_dir = os.environ.get("RT_SESSION_DIR")
    if session_dir:
        from ray_tpu._private import log_monitor

        try:
            out_p, err_p = log_monitor.redirect_stdio(
                session_dir, args.node_id or str(os.getpid())
            )
            log_paths = [("stdout", out_p), ("stderr", err_p)]
        except OSError:
            pass  # unwritable session dir: keep inherited stdio
    if resources.get("TPU", 0) <= 0:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # The env var alone is not enough: site hooks (e.g. a PJRT plugin
    # registered from sitecustomize) can programmatically force a platform at
    # interpreter start, silently overriding the inherited env and pointing
    # CPU-resource workers at the TPU. Backends initialize lazily, so
    # re-asserting the config right after jax's import wins — hooked lazily
    # so non-jax workloads don't pay the multi-second jax import at spawn.
    _install_jax_platform_pin()

    from ray_tpu._private import worker as worker_mod
    from ray_tpu._private.ids import JobID
    from ray_tpu._private.worker import CoreWorker

    core = CoreWorker(
        is_driver=False,
        gcs_addr=(args.gcs_host, args.gcs_port),
        job_id=JobID.from_hex(args.job_id),
        node_resources=resources,
        node_labels=json.loads(args.labels),
        standby=args.standby,
    )
    if args.node_id:
        core.node_id = args.node_id
    worker_mod.global_worker = core

    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    core.loop = loop
    loop.run_until_complete(core._async_setup())
    core._install_ref_hooks()
    if log_paths:
        from ray_tpu._private import log_monitor

        monitor = log_monitor.LogMonitor(core, log_paths)
        monitor.start()

    def handle_term(*_):
        loop.stop()

    signal.signal(signal.SIGTERM, handle_term)
    # RT_PROFILE_DIR: dump a cProfile of this process's event-loop thread on
    # exit (perf investigation tool; reference analog: py-spy in the
    # reporter agent).
    profile_dir = os.environ.get("RT_PROFILE_DIR")
    prof = None
    if profile_dir:
        import cProfile

        prof = cProfile.Profile()
        prof.enable()
    try:
        loop.run_forever()
    finally:
        if prof is not None:
            prof.disable()
            try:
                os.makedirs(profile_dir, exist_ok=True)
                prof.dump_stats(
                    os.path.join(profile_dir, f"worker-{os.getpid()}.pstats")
                )
            except Exception:
                pass
        sys.exit(0)


if __name__ == "__main__":
    main()
