"""Synchronous head-service client for out-of-band tools.

Used by the CLI, JobSubmissionClient, and the state API when there is no
initialized worker in the process (reference analog: the dashboard/state
tools talking straight to GCS RPC without a full ray.init()).
"""
from __future__ import annotations

import asyncio
import threading
from typing import Optional, Tuple

from ray_tpu._private import protocol


class SyncHeadClient:
    def __init__(self, address: str):
        host, _, port = address.rpartition(":")
        self.addr: Tuple[str, int] = (host or "127.0.0.1", int(port))
        self._loop = asyncio.new_event_loop()
        self._conn: Optional[protocol.Connection] = None
        ready = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(ready,), daemon=True,
            name="rt-sync-client",
        )
        self._thread.start()
        ready.wait(timeout=10)
        if self._conn is None:
            raise ConnectionError(f"cannot reach head at {address}")

    def _run(self, ready):
        asyncio.set_event_loop(self._loop)

        async def connect():
            try:
                self._conn = await protocol.connect(
                    self.addr, self._noop_handler, name="sync-client"
                )
            finally:
                ready.set()

        self._loop.run_until_complete(connect())
        if self._conn is not None:
            self._loop.run_forever()

    async def _noop_handler(self, method, header, frames, conn):
        return {}, []

    def call(self, method: str, header: dict, timeout: float = 30.0,
             frames: list = ()):
        fut = asyncio.run_coroutine_threadsafe(
            self._conn.call(method, header, frames), self._loop
        )
        return fut.result(timeout)

    def close(self):
        if self._conn is not None:
            asyncio.run_coroutine_threadsafe(
                self._conn.close(), self._loop
            ).result(timeout=5)
        self._loop.call_soon_threadsafe(self._loop.stop)
