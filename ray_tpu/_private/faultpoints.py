"""Deterministic failpoint injection plane.

Reference analog: the per-RPC testing hooks the reference core threads
through its gRPC client (``RAY_testing_rpc_failure`` consulted in
``src/ray/rpc/grpc_client.h`` — request/reply failures injected by method
name with a seeded probability). Random node kills (`NodeKiller`) only
exercise whole-process death; the recovery bugs that survive production
are the *partial* failures — a dropped reply after the verb applied, a
slow pull, a crash mid-dispatch (lineage-driven fault injection, Alvaro
et al. SIGMOD '15; chaos practice, Basiri et al. IEEE Software '16).

Every layer that crosses a process or host boundary declares **named
fault points** (the catalog below) and consults this module at the
boundary. A point fires according to a spec:

    RT_FAULT_SPEC="point:kind:prob[:count[:seed]],..."

e.g. ``RT_FAULT_SPEC="gcs.dispatch.lease:drop:0.1:0:42"`` drops 10% of
lease replies, deterministically (per-spec seeded RNG: the set of call
indices that inject is a pure function of ``seed``/``prob``, so two runs
inject at identical indices). Tests use :func:`configure` /
:func:`stats` / :func:`clear` instead of the env var.

Kinds:

- ``error`` — raise a transport-shaped exception (``err`` class chosen
  by the call site; carries ``code="unavailable"`` so retry policies can
  distinguish injected/transient unavailability from application errors)
- ``delay`` — inject latency (``delay_s``, default 0.05s), then proceed
- ``drop``  — lose the message *after* side effects: the call site skips
  the send / swallows the reply so the caller times out
- ``crash`` — hard-exit the process (``os._exit``), the real SIGKILL test

Cost when idle: every call site is gated on the module attribute
``ACTIVE`` (``if faultpoints.ACTIVE: ...``) — with no spec configured
the hot paths pay one attribute load and a false branch, nothing else.

Thread-safety: decisions (RNG draw + counters) run under a lock; the
injected sleep happens outside it. Determinism holds per spec as long as
the matching point fires from one thread (true for the event-loop points
— gcs dispatch, protocol send/reply/read; ring points fire on the pump
thread, also single-threaded per connection).
"""
from __future__ import annotations

import asyncio
import logging
import os
import random
import threading
import time
from typing import Dict, List, Optional

from ray_tpu._private import flight

logger = logging.getLogger(__name__)

KINDS = ("error", "delay", "drop", "crash")

# How many injected call indices each spec records for stats()/determinism
# assertions; beyond this only the counters keep growing.
_MAX_INDICES = 10_000


class DropReply(Exception):
    """Raised by a handler AFTER its side effects to make the RPC layer
    swallow the reply (protocol._dispatch / ringconn._handle_slow catch it
    and send nothing). The caller sees a timeout — the classic
    applied-but-unacknowledged partial failure."""


# name -> (layer, supported kinds, description). Wildcard entries (name
# ending in ``*``) cover a family of fired names, e.g. every head verb.
CATALOG: Dict[str, tuple] = {
    "protocol.rpc.send": (
        "protocol", ("error", "delay", "drop", "crash"),
        "client request send on a TCP connection "
        "(grpc_client.h request-path hook)"),
    "protocol.rpc.reply": (
        "protocol", ("error", "delay", "drop", "crash"),
        "server reply send: drop = verb applied, ack lost "
        "(grpc_client.h reply-path hook)"),
    "protocol.rpc.read": (
        "protocol", ("error", "delay", "drop", "crash"),
        "inbound frame read: error tears the connection down mid-stream"),
    "ring.push": (
        "ringconn", ("error", "delay", "drop", "crash"),
        "shm-ring send (request, reply, or notify)"),
    "ring.pop": (
        "ringconn", ("error", "delay", "drop", "crash"),
        "shm-ring receive: drop loses one message, error wedges the ring"),
    "gcs.dispatch.*": (
        "gcs", ("error", "delay", "drop", "crash"),
        "head verb dispatch, per verb (gcs.dispatch.lease, ...): error "
        "fails the verb before it runs, drop applies it and swallows the "
        "reply"),
    "gcs.lease.grant": (
        "gcs", ("error", "delay"),
        "lease-grant path inside rpc_lease, before any resource is "
        "acquired"),
    "gcs.actor.create": (
        "gcs", ("error", "delay"),
        "actor registration/scheduling entry (GcsActorManager "
        "HandleCreateActor analog); fires once per actor, including "
        "each item of a create_actor_batch"),
    "gcs.create_actor_batch": (
        "gcs", ("error", "delay"),
        "batched actor-creation verb entry, before ANY item registers: "
        "error fails the whole batch as retryable-unavailable (the "
        "client re-issues under its correlation id), leaving no "
        "half-created actors behind"),
    "gcs.pubsub.publish": (
        "gcs", ("error", "delay", "drop"),
        "head pubsub fan-out: drop/error lose the publish for every "
        "subscriber"),
    "worker.pull": (
        "worker", ("error", "delay", "drop", "crash"),
        "object pull from an owner (single and owner-coalesced batch)"),
    "worker.task.push": (
        "worker", ("error", "delay", "crash"),
        "task push onto a leased slot (PushNormalTask analog)"),
    "worker.spec.frame": (
        "worker", ("error", "delay"),
        "spec-template build on the submitting worker (one per "
        "(function, options)): error degrades that submission to the "
        "inline full-header path — framing is an optimization, never a "
        "correctness dependency"),
    "worker.task.exec": (
        "worker", ("delay", "crash"),
        "task execution entry on the EXECUTING worker (HandlePushTask "
        "analog): crash = the worker process dies mid-dispatch, after "
        "the lease was consumed and before any reply. No error kind: "
        "its semantics would diverge between the ring fast path (task "
        "result) and the TCP slow path (transport failure)"),
    "worker.actor.push": (
        "worker", ("error", "delay", "drop"),
        "actor-call push attempt (PushActorTask analog): drop = the "
        "request never reaches the actor worker; the caller's reply "
        "deadline fires and the corr-deduped retry re-delivers"),
    "worker.dispatch.retry": (
        "worker", ("error", "delay"),
        "dispatch-retry path after a failed push attempt"),
    "worker.push.window": (
        "worker", ("error", "delay", "drop"),
        "adaptive push-window pacing decision on the SUBMITTING worker "
        "(one per packed chunk): error degrades that chunk to the fixed "
        "pre-round-16 fan-out — pacing is an optimization, never a "
        "correctness gate; drop resets the slot's window to its floor "
        "(forces a cold re-ramp through the AIMD grow path); delay "
        "stalls the grant before the chunk packs"),
    "worker.reply.window": (
        "worker", ("error", "delay", "drop"),
        "coalesced multi-result reply flush on the EXECUTING worker "
        "(reply-plane sibling of worker.task.push): drop/error = the "
        "whole window frame is lost in transit — every rider's push "
        "deadline re-arms and the corr-deduped re-push replays the "
        "recorded outcome, never re-executes"),
    "worker.arg.intern": (
        "worker", ("error", "delay", "drop"),
        "argument interning, both sides: on the PUSHER error degrades "
        "that push to full arg frames and drop resets the peer's "
        "coverage (blobs re-sent, exercising re-cover); on the EXECUTOR "
        "error forces — and drop really performs — an interned-frame "
        "eviction right before lookup, so the typed arg_intern_miss "
        "error makes the pusher re-send the exact bytes"),
    "driver.settle.handoff": (
        "worker", ("error", "delay", "drop"),
        "reply-frame handoff to the driver's settle plane (round 20, "
        "one per coalesced frame batch): error/drop = the handoff is "
        "refused and THAT batch settles inline on the event loop — the "
        "plane is an optimization, never a correctness gate; no frame "
        "is ever lost. delay stalls the offer (backpressure: depth "
        "climbs toward the bounded queue's reject threshold)"),
    "driver.submit.pack": (
        "worker", ("error", "delay", "drop"),
        "per-task handoff to the driver's submission pack plane (round "
        "20): error/drop degrade THAT submission to the inline "
        "pack-and-enqueue path — the task is never lost, only "
        "un-offloaded; delay stalls the submitting caller thread, not "
        "the event loop"),
    "serve.replica.call": (
        "serve", ("error", "delay"),
        "handle->replica dispatch, client side, BEFORE the request can "
        "reach user code: error is failed over transparently to another "
        "replica (bounded, jittered) — the safe-retry half of the serve "
        "request lifecycle"),
    "serve.replica.stream": (
        "serve", ("error", "delay"),
        "mid-stream chunk pull on an open serve stream: error surfaces "
        "as a typed retryable terminal error (SSE error event / gRPC "
        "UNAVAILABLE), never a silent hang or truncation"),
    "serve.proxy.route": (
        "serve", ("error", "delay"),
        "ingress proxy route-table resolution: error maps to a "
        "retryable 503/UNAVAILABLE, not a bare 500"),
    "devstore.register": (
        "devstore", ("error", "delay", "drop"),
        "device-object directory registration at put(): error/drop lose "
        "the directory entry — readers degrade to pull-from-owner, which "
        "the owner can always serve (registration is an optimization, "
        "never a correctness dependency)"),
    "devstore.shard_pull": (
        "devstore", ("error", "delay", "drop"),
        "device-shard pull between a consumer and the owner (fires on "
        "both sides): error surfaces as a typed retryable "
        "code=unavailable failure retried against the owner, drop = the "
        "reply is lost and the attempt deadline re-arms — never a hang "
        "or a half-materialized array"),
    "devstore.reshard": (
        "devstore", ("error", "delay"),
        "consumer-side reshard (jax.device_put to the requested "
        "sharding): injected unavailability is retried bounded with "
        "jittered backoff"),
    "spill.write": (
        "spill", ("error", "delay"),
        "spill write to external storage (SpillObjects analog)"),
    "spill.restore": (
        "spill", ("error", "delay"),
        "spill restore read: injected failure = missing external copy "
        "(AsyncRestoreSpilledObject analog)"),
}

# True iff at least one spec is configured; hot-path gate.
ACTIVE = False

_lock = threading.Lock()
_specs: List["_Spec"] = []


class _Spec:
    __slots__ = ("point", "kind", "prob", "count", "seed", "delay_s",
                 "rng", "calls", "injected", "indices")

    def __init__(self, point: str, kind: str, prob: float, count: int,
                 seed: int, delay_s: float):
        self.point = point
        self.kind = kind
        self.prob = prob
        self.count = count          # max injections; 0 = unlimited
        self.seed = seed
        self.delay_s = delay_s
        self.rng = random.Random(seed)
        self.calls = 0              # matched fire()s seen
        self.injected = 0
        self.indices: List[int] = []  # call indices that injected

    def matches(self, name: str) -> bool:
        if self.point.endswith("*"):
            return name.startswith(self.point[:-1])
        return name == self.point


def _point_known(point: str) -> bool:
    """A spec point is valid when it names a catalog entry, is covered by
    a wildcard catalog entry, or is itself a wildcard covering at least
    one catalog entry."""
    bare = point[:-1] if point.endswith("*") else None
    for name in CATALOG:
        if name == point:
            return True
        if name.endswith("*") and point.startswith(name[:-1]):
            return True
        if bare is not None and name.startswith(bare):
            return True
    return False


def _supported_kinds(point: str) -> tuple:
    for name, (_layer, kinds, _desc) in CATALOG.items():
        if name == point or (name.endswith("*")
                             and point.startswith(name[:-1])):
            return kinds
        if point.endswith("*") and name.startswith(point[:-1]):
            return kinds
    return KINDS


def register(name: str, layer: str, kinds: tuple, description: str):
    """Extend the catalog (tests, plugins). Names must be new."""
    if name in CATALOG:
        raise ValueError(f"fault point {name!r} already registered")
    CATALOG[name] = (layer, tuple(kinds), description)


def parse_spec(spec: str, delay_s: float = 0.05) -> List[_Spec]:
    """``point:kind:prob[:count[:seed]],...`` -> specs. Loud on typos:
    an unknown point or unsupported kind is a config error, not a
    silently-never-firing chaos run."""
    out: List[_Spec] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) < 2 or len(fields) > 5:
            raise ValueError(
                f"bad fault spec {part!r}: want point:kind:prob[:count[:seed]]"
            )
        point, kind = fields[0], fields[1]
        if not _point_known(point):
            raise ValueError(
                f"unknown fault point {point!r} (catalog: {sorted(CATALOG)})"
            )
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r} (have {KINDS})")
        if kind not in _supported_kinds(point):
            raise ValueError(
                f"fault point {point!r} does not support kind {kind!r} "
                f"(supported: {_supported_kinds(point)})"
            )
        try:
            prob = float(fields[2]) if len(fields) > 2 and fields[2] else 1.0
            count = int(fields[3]) if len(fields) > 3 and fields[3] else 0
            seed = int(fields[4]) if len(fields) > 4 and fields[4] else 0
        except ValueError as e:
            raise ValueError(f"bad fault spec {part!r}: {e}") from None
        if not 0.0 <= prob <= 1.0:
            raise ValueError(f"bad fault prob {prob} in {part!r}")
        out.append(_Spec(point, kind, prob, count, seed, delay_s))
    return out


def configure(spec, delay_s: float = 0.05):
    """Install fault specs, replacing any current set. ``spec`` is the
    env-var string format or an iterable of prebuilt ``_Spec``s."""
    global ACTIVE
    if isinstance(spec, str):
        new = parse_spec(spec, delay_s)
    else:
        new = list(spec)
    with _lock:
        _specs[:] = new
        ACTIVE = bool(_specs)
    if new:
        logger.info(
            "fault injection active: %s",
            ", ".join(f"{s.point}:{s.kind}:{s.prob}" for s in new),
        )


def clear():
    """Remove every spec; fire() returns to the no-op fast path."""
    global ACTIVE
    with _lock:
        _specs.clear()
        ACTIVE = False


def stats() -> List[dict]:
    """Per-spec counters: matched calls, injections, and the call indices
    that injected (the determinism contract: same seed/prob -> same
    indices)."""
    with _lock:
        return [
            {
                "point": s.point, "kind": s.kind, "prob": s.prob,
                "count": s.count, "seed": s.seed, "calls": s.calls,
                "injected": s.injected, "indices": list(s.indices),
            }
            for s in _specs
        ]


def _decide(name: str) -> Optional[_Spec]:
    """One RNG draw per matching spec per call (count limits must not
    shift later draws, or determinism breaks); first hit wins."""
    hit = None
    with _lock:
        for s in _specs:
            if not s.matches(name):
                continue
            s.calls += 1
            if s.rng.random() >= s.prob:
                continue
            if s.count and s.injected >= s.count:
                continue
            if hit is None:
                s.injected += 1
                if len(s.indices) < _MAX_INDICES:
                    s.indices.append(s.calls - 1)
                hit = s
    return hit


def _raise_injected(spec: _Spec, name: str, err):
    e = err(
        f"injected fault at {name} "
        f"(spec {spec.point}:{spec.kind}, injection #{spec.injected})"
    )
    # Transient-unavailability class: retry policies branch on this code,
    # never on message text (reference: UNAVAILABLE status retried by
    # retryable_grpc_client.cc).
    try:
        e.code = "unavailable"
    except AttributeError:
        logger.debug("injected %s has no writable .code", type(e).__name__)
    raise e


def _resolve(spec: _Spec, name: str, err) -> Optional[str]:
    """Shared drop/crash/error tail for fire()/async_fire(); the delay
    kind stays with the caller (blocking sleep vs await)."""
    if spec.kind == "drop":
        return "drop"
    if spec.kind == "crash":
        logger.error("injected crash at %s", name)
        os._exit(17)
    _raise_injected(spec, name, err)


def fire(name: str, err=ConnectionError) -> Optional[str]:
    """Evaluate the point synchronously. Returns None (no injection),
    "delay" (latency already injected), or "drop" (the call site must
    lose the message). ``error`` raises ``err``; ``crash`` never returns."""
    spec = _decide(name)
    if spec is None:
        return None
    if flight.ENABLED:
        # Chaos forensics: every injection lands in the flight ring as an
        # instant event AND stamps the enclosing RPC span, so a failed
        # chaos run dumps a trace showing exactly where the plane bit.
        flight.note_fault(name, spec.kind)
    if spec.kind == "delay":
        time.sleep(spec.delay_s)
        return "delay"
    return _resolve(spec, name, err)


async def async_fire(name: str, err=ConnectionError) -> Optional[str]:
    """fire() for event-loop call sites: delay awaits instead of blocking
    the loop."""
    spec = _decide(name)
    if spec is None:
        return None
    if flight.ENABLED:
        flight.note_fault(name, spec.kind)
    if spec.kind == "delay":
        await asyncio.sleep(spec.delay_s)
        return "delay"
    return _resolve(spec, name, err)


def _load_env():
    """Process-start configuration from RT_FAULT_SPEC (also reachable via
    rt_config / _system_config propagation to spawned workers)."""
    try:
        from ray_tpu._private.config import rt_config

        spec = rt_config.fault_spec
    except Exception:
        spec = os.environ.get("RT_FAULT_SPEC", "")
    if spec:
        configure(spec)


_load_env()
