"""Typed config registry with env + cluster-wide overrides.

Reference analog: ``src/ray/common/ray_config_def.h`` (240 ``RAY_CONFIG``
entries) + ``includes/ray_config.pxi``: every tunable is DECLARED in one
place with a type and default, each is overridable per-process via the
``RT_<NAME>`` environment variable, and a driver can push cluster-wide
overrides with ``ray_tpu.init(_system_config={...})`` (reference:
``_system_config`` serialized into every raylet/GCS command line,
``gcs_server.h:72``). Here the propagation rides worker-spawn environments
(local nodes) and the head KV (``__system_config`` namespace, applied by
workers at registration).

Resolution order (highest wins): explicit env var > cluster _system_config
> declared default.

Usage::

    from ray_tpu._private.config import rt_config
    cap = rt_config.arena_bytes
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional


def _parse_bool(s: str) -> bool:
    return s.strip().lower() not in ("0", "false", "no", "off", "")


class _Entry:
    __slots__ = ("name", "type", "default", "doc")

    def __init__(self, name: str, type_: Callable, default, doc: str):
        self.name = name
        self.type = type_
        self.default = default
        self.doc = doc

    @property
    def env(self) -> str:
        return "RT_" + self.name.upper()


class ConfigRegistry:
    def __init__(self):
        self._entries: Dict[str, _Entry] = {}
        # cluster-wide overrides pushed via init(_system_config=...)
        self._system: Dict[str, Any] = {}

    def declare(self, name: str, type_: Callable, default, doc: str):
        self._entries[name] = _Entry(name, type_, default, doc)

    def entries(self) -> Dict[str, _Entry]:
        return dict(self._entries)

    def validate_system_config(self, overrides: Dict[str, Any]):
        unknown = set(overrides) - set(self._entries)
        if unknown:
            raise ValueError(
                f"unknown _system_config key(s): {sorted(unknown)}; "
                f"declared: {sorted(self._entries)}"
            )

    def apply_system_config(self, overrides: Dict[str, Any]):
        """Install cluster-wide overrides in this process (values are
        re-parsed through the declared type so strings from the KV work)."""
        self.validate_system_config(overrides)
        for k, v in overrides.items():
            e = self._entries[k]
            if e.type is bool:
                self._system[k] = (
                    _parse_bool(v) if isinstance(v, str) else bool(v)
                )
            elif isinstance(v, str) and e.type is not str:
                self._system[k] = e.type(v)
            else:
                self._system[k] = e.type(v) if e.type is not str else str(v)

    def system_config(self) -> Dict[str, Any]:
        return dict(self._system)

    def system_config_env(self) -> Dict[str, str]:
        """The overrides as RT_* env vars for spawned worker processes —
        the local propagation channel (reference: _system_config on the
        raylet command line)."""
        return {
            self._entries[k].env: str(v) for k, v in self._system.items()
        }

    def get(self, name: str):
        e = self._entries[name]
        raw = os.environ.get(e.env)
        if raw is not None:
            try:
                return e.type(raw) if e.type is not bool else _parse_bool(raw)
            except (TypeError, ValueError):
                import logging

                logging.getLogger(__name__).warning(
                    "ignoring unparseable %s=%r (expected %s)",
                    e.env, raw, e.type.__name__,
                )
        if name in self._system:
            return self._system[name]
        return e.default

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        try:
            return self.get(name)
        except KeyError:
            raise AttributeError(name) from None


rt_config = ConfigRegistry()

# ---------------------------------------------------------------- registry
# One declaration per tunable (reference: ray_config_def.h). Env var is
# RT_<NAME>; most existed as scattered os.environ reads before round 4.

rt_config.declare(
    "arena_bytes", int, 4 << 30,
    "Native shm arena capacity per session (plasma-equivalent store size).")
rt_config.declare(
    "data_cpu_fraction", float, 0.5,
    "Fraction of cluster CPUs the data streaming executor may occupy "
    "(split across a driver's active operators, min one task each). "
    "Keeps ingest from starving co-located train/serve actors "
    "(reference: execution/resource_manager.py budgets).")
rt_config.declare(
    "auth_token", str, "",
    "Cluster auth token (reference: src/ray/rpc/authentication/ token "
    "auth). Minted at head start and required as the FIRST message on "
    "every control/xfer TCP connection; a reachable head port without it "
    "is a full cluster takeover. Empty = auth disabled (tests/dev).")
rt_config.declare(
    "oom_kill", bool, True,
    "Kill subprocess-backed retriable tasks under memory pressure "
    "(newest-first, grouped by owner) so the node survives a leaky "
    "workload; the owner retries elsewhere. Admission rejection stays on "
    "either way.")
rt_config.declare(
    "gc_tuning", bool, True,
    "Tune CPython's cyclic GC at worker/driver startup: freeze the "
    "post-import heap and raise collection thresholds. Millions of live "
    "framework objects (refs, lineage, pending queues) make default-cadence "
    "full collections O(heap) pauses on the hot path; measured 1.33x on "
    "sustained task submission. Set RT_GC_TUNING=0 to keep CPython "
    "defaults.")
rt_config.declare(
    "disable_native_store", bool, False,
    "Force the portable per-segment store even when the native arena "
    "builds (diagnostics).")
rt_config.declare(
    "native_xfer", bool, True,
    "Serve shm objects over the native C++ TCP transfer plane.")
rt_config.declare(
    "native_sched", bool, True,
    "Use the native C++ resource scheduler in the head.")
rt_config.declare(
    "native_ring", bool, True,
    "Use the shm ring fast-dispatch plane for same-host task/actor calls.")
rt_config.declare(
    "spill_dir", str, "",
    "Directory for object spills (default: session temp dir).")
rt_config.declare(
    "memory_threshold", float, 0.95,
    "Host memory fraction above which the OOM defense engages "
    "(reference: memory_usage_threshold).")
rt_config.declare(
    "lineage_bytes", int, 256 << 20,
    "Max bytes of task lineage retained for object reconstruction "
    "(reference: max_lineage_bytes).")
rt_config.declare(
    "head_reconnect_s", float, 60.0,
    "How long workers/drivers retry the head connection before giving up "
    "(live-cluster rejoin window).")
rt_config.declare(
    "runtime_env_dir", str, "",
    "Cache directory for runtime-env venvs/packages.")
rt_config.declare(
    "cluster_state_dir", str, "",
    "Directory for cluster launcher state files.")
rt_config.declare(
    "profile_dir", str, "",
    "Dump per-process cProfile stats here on exit (diagnostics).")
rt_config.declare(
    "stream_window", int, 16,
    "Streaming-generator flow control: max items a producer runs ahead "
    "of consumer acknowledgments.")
rt_config.declare(
    "lease_idle_s", float, 1.0,
    "How long a worker caches an idle task lease before returning it "
    "(reference: idle worker reaping).")
rt_config.declare(
    "health_check_period_s", float, 2.0,
    "Head liveness probe interval per node "
    "(reference: health_check_period_ms).")
rt_config.declare(
    "rpc_deadline_s", float, 30.0,
    "Per-attempt deadline for head/worker control RPCs. A dropped reply "
    "surfaces as a timeout at this horizon instead of hanging the verb "
    "forever; retryable verbs re-issue with jittered backoff "
    "(reference: retryable_grpc_client.cc timeouts).")
rt_config.declare(
    "rpc_retries", int, 2,
    "Extra attempts for deadline-bounded head RPCs after a timeout, "
    "connection loss, or an 'unavailable' error (reference: UNAVAILABLE "
    "retries in retryable_grpc_client.cc). Non-idempotent verbs carry a "
    "correlation id so a retry after a dropped reply never double-applies.")
rt_config.declare(
    "lease_request_timeout_s", float, 30.0,
    "How long the head may block a lease request waiting for resources "
    "before returning empty; the client's per-attempt RPC deadline sits "
    "above this.")
rt_config.declare(
    "flight_enabled", bool, False,
    "Record RPC/phase events into the per-process flight-recorder ring "
    "(_private/flight.py): verb spans on protocol send/reply, ring "
    "push/pop, head dispatch (queue-wait vs handler), worker "
    "pulls/pushes. Off: every hook costs one boolean. On: events go into "
    "a preallocated ring of flight_ring_size tuples; drain cluster-wide "
    "with `rt flight`. Propagates to spawned workers via the "
    "environment (RT_FLIGHT_ENABLED=1).")
rt_config.declare(
    "flight_ring_size", int, 16384,
    "Events retained per process by the flight recorder (fixed "
    "preallocated ring; oldest events are overwritten and counted as "
    "dropped in drain output).")
rt_config.declare(
    "flight_sample_n", int, 0,
    "Flight-recorder sampling: record 1 of every N spans (deterministic "
    "counter, not RNG — two identical runs sample identical call "
    "indices). 0/1 = record every span. Sampling makes an always-on "
    "recorder cheap enough for production: at N=100 the ring holds a "
    "100x longer window for the same memory and the per-span cost is "
    "one counter bump for the skipped 99.")
rt_config.declare(
    "memtrack_enabled", bool, True,
    "Object & memory observability plane (_private/memtrack.py): stamp "
    "owner/node into directory registrations, answer memstat_drain with "
    "owner-side object accounting, and push the rt_object_store_bytes / "
    "rt_object_count / arena / spill / memory-pressure gauges every "
    "metrics tick. Accounting is snapshot-time work over structures the "
    "refcount plane already keeps — the put/get hot paths pay nothing — "
    "so it defaults ON; RT_MEMTRACK_ENABLED=0 reduces every hook to one "
    "boolean (`rt memory` and the leak SLO then report nothing).")
rt_config.declare(
    "device_objects", bool, True,
    "Device-plane object store (_private/devstore.py): put() of a "
    "top-level jax.Array registers structured metadata {dtype, shape, "
    "sharding, placement, nbytes} in the head directory while the bytes "
    "stay on device; get() moves shards peer-to-peer (jax.device_put "
    "over ICI for same-slice peers, per-shard host buffers over "
    "pull_device_shards for cross-slice/DCN) and materializes with the "
    "consumer's sharding. Effective only when jax is importable; OFF "
    "(RT_DEVICE_OBJECTS=0) restores the byte-identical host cloudpickle "
    "path for jax arrays.")
rt_config.declare(
    "warm_workers", int, 0,
    "Warm worker pool: number of STANDBY node processes the local "
    "cluster preforks at init. Standby nodes register with the head but "
    "are excluded from scheduling until activated — the head activates "
    "one instantly when demand outgrows schedulable capacity, and "
    "LocalCluster.add_node consumes one instead of paying a cold "
    "process spawn (~2-4s). 0 disables the pool (reference: idle worker "
    "pool prestarts in worker_pool.cc).")
rt_config.declare(
    "actor_create_batch", bool, True,
    "Batch anonymous actor creations into create_actor_batch head RPCs: "
    "ActorClass.remote() returns immediately and a burst of N creations "
    "costs O(bursts) head round-trips instead of N (reference: async "
    "actor registration in GcsActorManager). Named / get_if_exists / "
    "detached creations always use the synchronous per-actor verb. Off: "
    "every creation blocks on its own head RPC (pre-round-10 behavior).")
rt_config.declare(
    "reply_batching", bool, True,
    "Reply-plane batching: executor-side results from the same peer "
    "connection coalesce into multi-result frames flushed by a "
    "self-clocking window (first result flushes immediately, the rest "
    "ride the in-flight frame's ack — specframe.ReplyWindow, the "
    "create_actor_batch discipline mirrored onto replies), and plain "
    "push_task gains per-task corr dedup + deadline re-arm so a dropped "
    "frame replays recorded outcomes instead of hanging or re-executing. "
    "Off (RT_REPLY_BATCHING=0): every result is acked one by one on the "
    "pre-round-15 per-task reply path, byte-identically.")
rt_config.declare(
    "reply_window_max", int, 128,
    "Max results one reply window accumulates before flushing mid-ack "
    "(memory/latency cap on coalescing; the byte cap below also applies).")
rt_config.declare(
    "reply_window_bytes", int, 256 << 10,
    "Max buffered result bytes per reply window before a forced flush — "
    "kept well under the shm ring's message limit so a coalesced frame "
    "never degrades to the per-item too-big fallback.")
rt_config.declare(
    "reply_window_horizon_s", float, 1.0,
    "Ack horizon for an in-flight TCP reply window: if the receiving "
    "pump's mrack is lost, the next completing result re-arms the window "
    "after this long instead of buffering forever.")
rt_config.declare(
    "reply_window_gap_s", float, 0.001,
    "Flush pacing for ring reply windows (timer-clocked: results within "
    "one gap of the last flush coalesce, a deferred tail flush covers "
    "the stragglers). Ring windows pace by time instead of mrack acks "
    "because the ack traffic contends with the pusher on the ring send "
    "lock; this is also the worst case added to a lone result's reply "
    "latency.")
rt_config.declare(
    "arg_interning", bool, True,
    "Per-peer argument interning on the push path: small argument frames "
    "are content-hashed and shipped ONCE per (peer, digest) the way "
    "FnPushLedger piggybacks function blobs; subsequent pushes carry only "
    "the digest and the executor re-inserts the exact bytes from its "
    "bounded LRU (miss/eviction => typed arg_intern_miss, pusher re-sends "
    "the blob). Off (RT_ARG_INTERNING=0): every push carries full arg "
    "frames, byte-identically to the pre-round-15 wire.")
rt_config.declare(
    "arg_intern_min_bytes", int, 128,
    "Smallest argument frame worth interning (digest + header entry "
    "overhead must stay well under the bytes saved).")
rt_config.declare(
    "arg_intern_max_bytes", int, 256 << 10,
    "Largest argument frame the interning plane will cache per peer "
    "(bigger payloads should ride refs/shm, not per-task frames).")
rt_config.declare(
    "arg_intern_cache_bytes", int, 64 << 20,
    "Executing-side interned-argument LRU capacity in bytes; eviction "
    "only costs a re-send of the blob on the next digest-only push.")
rt_config.declare(
    "push_window", bool, True,
    "Adaptive in-flight push windows (specframe.PushWindow): each leased "
    "slot paces how many tasks sit between the driver's pending queue "
    "and the executor pool by an AIMD congestion window clocked on "
    "observed chunk-settle latency — additive grow on clean drains, "
    "multiplicative shrink when transit/exec-queue latency inflates — "
    "instead of the fixed 16-pusher x 16-task fan-out. The live window "
    "is exported as rt_push_window{peer} on /metrics. Off "
    "(RT_PUSH_WINDOW=0): the pre-round-16 static fan-out, "
    "byte-identically.")
rt_config.declare(
    "push_window_initial", int, 64,
    "Starting push-window size per leased slot, in tasks (four full "
    "ring chunks: pipelining from the first pump, headroom to ramp).")
rt_config.declare(
    "push_window_floor", int, 4,
    "Smallest push window a saturated slot shrinks to: enough to keep "
    "one chunk on the wire while the previous settles, small enough "
    "that a wedged executor never accumulates parked chunks.")
rt_config.declare(
    "push_window_ceiling", int, 256,
    "Largest push window a slot grows to — the pre-round-16 static "
    "worst case (16 pushers x 16-task chunks), so pacing can only "
    "remove queueing, never add fan-out beyond what the fixed plan "
    "allowed.")
rt_config.declare(
    "push_window_latency_factor", float, 6.0,
    "Chunk push->reply-arrival latency above this multiple of the "
    "tracked clean baseline reads as congestion (multiplicative "
    "shrink). The baseline tracks the minimum observed latency with a "
    "slow upward drift so a durably slower workload re-baselines "
    "instead of shrinking forever. Measured on the 1-core A/B box: 3.0 "
    "over-shrank (the window thrashed at ~37 against a 2ms base while "
    "the executor still had headroom), 6.0 settles at 40-100 with "
    "single-digit shrinks per 5k burst.")
rt_config.declare(
    "pump_batch_drain", bool, True,
    "Batched ring-pump handoff: the pump thread hands EVERY message of "
    "one ring drain to the executor-side batch dispatch in one pass — "
    "one corr-claim pass and O(task slots) executor wakeups per drain "
    "instead of per message. Off (RT_PUMP_BATCH_DRAIN=0): per-message "
    "dispatch, the pre-round-16 pump behavior.")
rt_config.declare(
    "settle_batching", bool, True,
    "Multi-frame driver settling: inside a get()/wait() window the "
    "driver's TCP recv loop drains every already-buffered reply frame "
    "before yielding, so one loop wakeup settles several coalesced "
    "frames' futures (the ring pump already batches per drain). "
    "Disabled automatically while fault injection is active so chaos "
    "specs keep their per-message determinism. Off "
    "(RT_SETTLE_BATCHING=0): one frame per recv wakeup, the "
    "pre-round-16 loop.")
rt_config.declare(
    "serve_request_timeout_s", float, 60.0,
    "Serve proxy per-request deadline (HTTP and gRPC ingress). A request "
    "that has not produced a result within this horizon is failed with "
    "504 + Retry-After (DEADLINE_EXCEEDED on gRPC) instead of holding a "
    "proxy slot forever (reference: Serve request_timeout_s).")
rt_config.declare(
    "serve_max_inflight", int, 512,
    "Serve proxy global admission cap: max requests (streams included) a "
    "proxy holds in flight at once. Beyond it new requests are shed with "
    "503 + Retry-After (RESOURCE_EXHAUSTED on gRPC) before any routing "
    "work happens — saturation degrades to fast typed rejections, not "
    "collapse. 0 = unbounded (reference: proxy backpressure semantics).")
rt_config.declare(
    "serve_drain_deadline_s", float, 30.0,
    "Graceful replica drain deadline on scale-down/redeploy: the "
    "controller stops routing to the replica, waits for in-flight "
    "requests and open streams to finish up to this horizon, then stops "
    "it. Requests still running at the deadline are cut (reference: "
    "Serve graceful_shutdown_timeout_s + proxy draining).")
rt_config.declare(
    "serve_failover_attempts", int, 2,
    "Extra replica picks a deployment handle tries when a request fails "
    "BEFORE reaching user code (replica dead at submit, transport "
    "refused). Failures after user code may have run are never replayed "
    "transparently — they surface as a typed retryable error the client "
    "decides about (reference: Serve router retry on "
    "ActorUnavailable before execution).")
rt_config.declare(
    "serve_stream_chunk_timeout_s", float, 300.0,
    "Per-chunk deadline for serve streaming responses (handle-side "
    "next_chunks pull and proxy SSE forwarding): a wedged replica "
    "terminates the stream with a typed error event instead of hanging "
    "the client forever.")
rt_config.declare(
    "fault_spec", str, "",
    "Deterministic fault injection spec "
    "('point:kind:prob[:count[:seed]],...' — see _private/faultpoints.py "
    "catalog). Empty disables injection entirely (hot paths pay one "
    "boolean check). Reference: RAY_testing_rpc_failure hooks in "
    "src/ray/rpc/grpc_client.h.")
rt_config.declare(
    "driver_settle_thread", bool, True,
    "Driver settle plane (round 20): coalesced reply frames from the "
    "TCP recv loop hand off to a dedicated settle worker thread that "
    "splits/decodes them off-loop and settles futures in batches — "
    "ONE call_soon_threadsafe per drain per target loop, never one "
    "per frame. The ring pump never queues to the plane (it is itself "
    "off-loop): attachment switches it to prepare each drain's "
    "replies in place on the pump thread. The handoff queue is "
    "bounded (full queue degrades that frame to the inline on-loop "
    "path, so backpressure never loses a reply) and its depth exports "
    "as rt_settle_queue_depth. Driver-only; the carved-out wait "
    "appears as the settle-dwell task phase. Auto stand-down on "
    "single-core hosts (the plane thread would contend with the loop "
    "for the GIL) unless RT_DRIVER_SETTLE_THREAD is set explicitly. "
    "Off (RT_DRIVER_SETTLE_THREAD=0): replies settle inline on the "
    "recv/pump wakeup, the pre-round-20 behavior (reference: the "
    "dedicated reply-handling asio loop in core_worker's "
    "client_call_manager).")
rt_config.declare(
    "submit_pack_thread", bool, True,
    "Driver submission pack plane (round 20): submit_task hands the "
    "per-task wire-size accounting, lineage bookkeeping, and dispatch "
    "enqueue to a pack worker thread that feeds the event loop "
    "pre-framed batches — one loop wakeup and one lease pump per "
    "submit burst instead of one per task, shrinking the submit-queue "
    "leg at 5k-task scale. Bounded handoff; a full queue (or the "
    "driver.submit.pack faultpoint) degrades that submission to the "
    "inline _enqueue_dispatch path, so no task is ever lost. Off "
    "(RT_SUBMIT_PACK_THREAD=0): submissions enqueue inline from the "
    "caller thread, the pre-round-20 behavior (reference: the "
    "CoreWorker submit queue draining on its dedicated io thread).")
rt_config.declare(
    "pusher_loop_shards", int, -1,
    "Sharded pusher event loops (round 20, driver-only): lease slots "
    "hash by peer address onto N dedicated pusher loops, each owning "
    "its peers' PushWindows, so chunk packing and push pacing stop "
    "serializing behind the driver's main loop. -1 = auto "
    "(min(2, cores-1); 0 on small hosts), 0 = off: pushers run on the "
    "main loop, the pre-round-20 behavior. Cross-loop touches (peer/"
    "ring connect, task-reply application, slot bookkeeping) marshal "
    "to the main loop; a slot never migrates between shards "
    "(reference: core_worker's per-connection asio strands).")
