"""Core microbenchmarks (reference analog: ``python/ray/_private/ray_perf.py``
run by ``release/microbenchmark/run_microbenchmark.py`` — same workload shapes
so numbers are directly comparable to BASELINE.md)."""
from __future__ import annotations

import os
import time
from typing import Dict

import numpy as np

import ray_tpu


def _rate(n, t):
    return n / t if t > 0 else float("inf")


def bench_single_client_tasks_async(n: int = 2000) -> float:
    @ray_tpu.remote
    def noop():
        return None

    ray_tpu.get([noop.remote() for _ in range(50)])  # warm the lease path
    t0 = time.perf_counter()
    refs = [noop.remote() for _ in range(n)]
    ray_tpu.get(refs)
    return _rate(n, time.perf_counter() - t0)


def bench_single_client_tasks_sync(n: int = 300) -> float:
    @ray_tpu.remote
    def noop():
        return None

    ray_tpu.get(noop.remote())
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(noop.remote())
    return _rate(n, time.perf_counter() - t0)


def bench_actor_calls_async(n: int = 2000) -> float:
    @ray_tpu.remote
    class A:
        def m(self):
            return None

    a = A.remote()
    ray_tpu.get(a.m.remote())
    t0 = time.perf_counter()
    ray_tpu.get([a.m.remote() for _ in range(n)])
    rate = _rate(n, time.perf_counter() - t0)
    ray_tpu.kill(a)  # release the actor's CPU for the later benches
    return rate


def bench_actor_calls_sync(n: int = 300) -> float:
    @ray_tpu.remote
    class A:
        def m(self):
            return None

    a = A.remote()
    ray_tpu.get(a.m.remote())
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(a.m.remote())
    rate = _rate(n, time.perf_counter() - t0)
    ray_tpu.kill(a)
    return rate


def bench_actor_calls_1_n(n: int = 2000, n_actors: int = 0) -> float:
    """One caller fanning async calls across N actors (reference:
    1_n_actor_calls_async in ray_perf)."""
    if n_actors <= 0:
        n_actors = max(min((os.cpu_count() or 1), 8), 2)

    @ray_tpu.remote
    class A:
        def m(self):
            return None

    actors = [A.remote() for _ in range(n_actors)]
    ray_tpu.get([a.m.remote() for a in actors])
    t0 = time.perf_counter()
    refs = [actors[i % n_actors].m.remote() for i in range(n)]
    ray_tpu.get(refs)
    rate = _rate(n, time.perf_counter() - t0)
    for a in actors:
        ray_tpu.kill(a)
    return rate


def bench_actor_calls_concurrent(n: int = 1000) -> float:
    """Async calls against one max_concurrency=10 actor (reference:
    1_1_actor_calls_concurrent)."""
    @ray_tpu.remote
    class A:
        def m(self):
            return None

    a = A.options(max_concurrency=10).remote()
    ray_tpu.get(a.m.remote())
    t0 = time.perf_counter()
    ray_tpu.get([a.m.remote() for _ in range(n)])
    rate = _rate(n, time.perf_counter() - t0)
    ray_tpu.kill(a)
    return rate


def bench_async_actor_calls(n: int = 1000) -> float:
    """Async (coroutine-method) actor throughput (reference:
    1_1_async_actor_calls_async)."""
    @ray_tpu.remote
    class A:
        async def m(self):
            return None

    a = A.remote()
    ray_tpu.get(a.m.remote())
    t0 = time.perf_counter()
    ray_tpu.get([a.m.remote() for _ in range(n)])
    rate = _rate(n, time.perf_counter() - t0)
    ray_tpu.kill(a)
    return rate


def _client_actor_burst(addr: str, n: int, q):
    """Subprocess body for n_n actor calls: each client owns one actor."""
    import time as _time

    import ray_tpu as rt

    rt.init(address=addr)

    @rt.remote
    class A:
        def m(self):
            return None

    a = A.remote()
    rt.get([a.m.remote() for _ in range(50)])
    t0 = _time.perf_counter()
    rt.get([a.m.remote() for _ in range(n)])
    q.put((os.getpid(), n / (_time.perf_counter() - t0)))
    rt.kill(a)  # return the actor's CPU before exiting — leaked actors
    rt.shutdown()  # would starve every later bench leg


def bench_actor_calls_n_n(clients: int = 4, n: int = 1000) -> float:
    """Aggregate actor-call throughput across N driver processes, each with
    its own actor (reference: n_n_actor_calls_async). Sum of per-client
    steady-state rates."""
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.get_global_worker()
    addr = f"{w.gcs_addr[0]}:{w.gcs_addr[1]}"
    rates, _ = _run_clients(
        _client_actor_burst, [(addr, n) for _ in range(clients)],
        timeout=900.0,
    )
    return float(sum(rates))


def bench_put_gigabytes(total_gb: float = 2.0) -> float:
    """Large-object put throughput (reference shape: ray_perf puts numpy
    arrays; zero-copy serialization means one memcpy into the arena). Refs
    drop as we go — sustained throughput recycles hot arena pages the way
    a training feed does."""
    chunk = np.random.rand(100 * 1024 * 1024 // 8)  # 100MB float64
    n = max(int(total_gb * 1024 / 100), 1)
    ref = ray_tpu.put(chunk)  # warm: arena creation + page faults
    del ref
    t0 = time.perf_counter()
    for _ in range(n):
        ref = ray_tpu.put(chunk)
        del ref
    dt = time.perf_counter() - t0
    return n * chunk.nbytes / (1024 ** 3) / dt


def bench_put_get_device(total_gb: float = 0.5) -> float:
    """Device-plane put/get throughput: a sharded jax.Array crosses
    put()→get() into ANOTHER process (the pull_device_shards DCN leg —
    the same-process path is a table hit and measures nothing). Recorded
    as ``put_get_device_gb_per_s`` next to ``single_client_put_gb_per_s``
    so the device plane's trajectory rides the same bench JSON."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devs = jax.devices()
    n_shard = min(len(devs), 4)
    mesh = Mesh(np.array(devs[:n_shard]), ("x",))
    rows = 64 * 1024 * n_shard  # ~64MB float32 at 256 cols
    arr = jax.device_put(
        jnp.ones((rows, 256), jnp.float32),
        NamedSharding(mesh, PartitionSpec("x")),
    )
    nbytes = int(arr.nbytes)

    @ray_tpu.remote(num_cpus=1)
    class Consumer:
        def consume(self, ref):
            import numpy as _np

            # Deliberate: the bench measures exactly this consumer-side
            # resolve; one actor on an elastic pool cannot deadlock it.
            v = ray_tpu.get(ref[0])  # raytpu: ignore[RT102]
            return int(_np.asarray(v).shape[0])

    c = Consumer.remote()
    warm = ray_tpu.put(arr)
    assert ray_tpu.get(c.consume.remote([warm]), timeout=120) == rows
    del warm
    n = max(int(total_gb * (1024 ** 3) / nbytes), 1)
    t0 = time.perf_counter()
    for _ in range(n):
        ref = ray_tpu.put(arr)
        # Consumer caches per-oid, and each put is a fresh oid: every
        # round pays the full shard pull.
        ray_tpu.get(c.consume.remote([ref]), timeout=120)
        del ref
    dt = time.perf_counter() - t0
    ray_tpu.kill(c)
    return n * nbytes / (1024 ** 3) / dt


def bench_get_calls(n: int = 2000) -> float:
    ref = ray_tpu.put(np.zeros(1000, np.float64))  # ~8KB, memory-store path
    ray_tpu.get(ref)
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(ref)
    return _rate(n, time.perf_counter() - t0)


def _client_task_burst(addr: str, n: int, q):
    """Subprocess body for the multi-client benches (spawn-safe)."""
    import time as _time

    import ray_tpu as rt

    rt.init(address=addr)

    @rt.remote
    def noop():
        return None

    rt.get([noop.remote() for _ in range(50)])
    t0 = _time.perf_counter()
    rt.get([noop.remote() for _ in range(n)])
    q.put((os.getpid(), n / (_time.perf_counter() - t0)))
    rt.shutdown()


def _client_put_burst(addr: str, total_mb: int, q):
    import time as _time

    import numpy as _np

    import ray_tpu as rt

    rt.init(address=addr)
    chunk = _np.random.rand(50 * 1024 * 1024 // 8)  # 50MB
    n = max(total_mb // 50, 1)
    r = rt.put(chunk)
    del r
    t0 = _time.perf_counter()
    for _ in range(n):
        r = rt.put(chunk)
        del r
    q.put((os.getpid(), n * chunk.nbytes / (1024 ** 3) / (_time.perf_counter() - t0)))
    rt.shutdown()


def _run_clients(target, args_list, timeout=300.0):
    """Run client subprocesses concurrently; returns (results, wall_s).
    Reports are (pid, value) pairs, so a client that exits without ever
    reporting aborts the wait promptly, while one that reported and then
    exited nonzero (e.g. an error inside rt.shutdown) is still counted."""
    import multiprocessing as mp
    import queue as queue_mod

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [
        ctx.Process(target=target, args=(*a, q)) for a in args_list
    ]
    t0 = time.perf_counter()
    try:
        for p in procs:
            p.start()
        out = []
        reported = set()
        deadline = time.perf_counter() + timeout
        while len(out) < len(procs):
            try:
                pid, val = q.get(timeout=1.0)
                reported.add(pid)
                out.append(val)
                continue
            except queue_mod.Empty:
                pass
            if time.perf_counter() > deadline:
                raise RuntimeError("bench clients timed out")
            silent_dead = [
                p for p in procs
                if not p.is_alive() and p.pid not in reported
            ]
            if silent_dead and q.empty():
                raise RuntimeError(
                    f"{len(silent_dead)} bench client(s) exited "
                    "before reporting"
                )
        wall = time.perf_counter() - t0
        return out, wall
    finally:
        for p in procs:
            try:
                p.join(timeout=5)
                if p.is_alive():
                    p.kill()
            except (ValueError, AssertionError):
                pass  # never started (start() itself raised)


def bench_multi_client_tasks_async(clients: int = 4, n: int = 1000) -> float:
    """Aggregate async-task throughput across independent driver processes
    (reference: multi_client_tasks_async in ray_perf / release benchmarks).
    Reported as the SUM of per-client steady-state rates: client startup
    (jax import etc.) is excluded, and on hosts too small to overlap all
    clients this is an upper bound on sustained concurrent throughput."""
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.get_global_worker()
    addr = f"{w.gcs_addr[0]}:{w.gcs_addr[1]}"
    rates, _ = _run_clients(
        _client_task_burst, [(addr, n) for _ in range(clients)],
        timeout=900.0,
    )
    # Sum of per-client rates (reference semantics): client process startup
    # (jax import etc.) must not dilute the steady-state number.
    return float(sum(rates))


def bench_multi_client_put(clients: int = 4, total_mb: int = 500) -> float:
    """Aggregate put bandwidth (GB/s) across driver processes."""
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.get_global_worker()
    addr = f"{w.gcs_addr[0]}:{w.gcs_addr[1]}"
    rates, _ = _run_clients(
        _client_put_burst, [(addr, total_mb) for _ in range(clients)],
        timeout=900.0,
    )
    return float(sum(rates))


def bench_put_calls(n: int = 1000) -> float:
    """Small-object put ops/s through the shm store (reference:
    single_client_put_calls_Plasma_Store in ray_perf — per-put fixed cost:
    create/seal/register, not bandwidth). 1MB payloads clear the inline
    threshold so every put exercises the arena."""
    chunk = np.random.rand(1024 * 1024 // 8)  # 1MB > INLINE_OBJECT_MAX
    ref = ray_tpu.put(chunk)
    del ref
    t0 = time.perf_counter()
    for _ in range(n):
        ref = ray_tpu.put(chunk)
        del ref
    return _rate(n, time.perf_counter() - t0)


def bench_get_10k_refs(k: int = 10_000) -> float:
    """ops/s for getting one object that contains 10k nested ObjectRefs
    (reference: single_client_get_object_containing_10k_refs — stresses
    borrow registration and nested-ref resolution)."""
    vals = [ray_tpu.put(i) for i in range(k)]
    container = ray_tpu.put(vals)
    n = 5
    ray_tpu.get(container)  # warm
    t0 = time.perf_counter()
    for _ in range(n):
        inner = ray_tpu.get(container)
    dt = time.perf_counter() - t0
    del inner, vals, container
    return _rate(n, dt)


def bench_wait_1k_refs(k: int = 1000) -> float:
    """ops/s for ray.wait over 1k pending refs (reference:
    single_client_wait_1k_refs)."""
    @ray_tpu.remote
    def quick():
        return None

    refs = [quick.remote() for _ in range(k)]
    ray_tpu.get(refs)  # all ready: wait() measures bookkeeping, not tasks
    n = 5
    ray_tpu.wait(refs, num_returns=len(refs), timeout=10)
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.wait(refs, num_returns=len(refs), timeout=10)
    return _rate(n, time.perf_counter() - t0)


def bench_get_actor_refs(k: int = 1000, actors: int = 2) -> float:
    """refs/s for a multi-ref get whose objects live in OTHER workers'
    memory stores (no shm directory entry): exercises the batched
    directory lookup + owner-coalesced pull path — O(owners) RPCs per
    get, not O(refs)."""
    @ray_tpu.remote
    class Holder:
        def make(self, n, base):
            return [ray_tpu.put(base + i) for i in range(n)]

    hs = [Holder.remote() for _ in range(actors)]
    per = k // actors
    refs = []
    for j, h in enumerate(hs):
        refs.extend(ray_tpu.get(h.make.remote(per, j * per)))
    ray_tpu.get(refs)  # warm (pulled values are not cached; resolve repeats)
    n = 3
    t0 = time.perf_counter()
    for _ in range(n):
        out = ray_tpu.get(refs)
    dt = time.perf_counter() - t0
    assert out[0] == 0 and out[-1] == len(refs) - 1
    for h in hs:
        ray_tpu.kill(h)
    return _rate(n * len(refs), dt)


def bench_pg_churn(n: int = 50) -> float:
    """Placement-group create/ready/remove cycles per second (reference
    baseline: placement_group create/removal rate in BASELINE.md)."""
    from ray_tpu.util.placement_group import (
        placement_group,
        remove_placement_group,
    )

    t0 = time.perf_counter()
    for _ in range(n):
        pg = placement_group([{"CPU": 0.01}])
        pg.ready(timeout=30)
        remove_placement_group(pg)
    return _rate(n, time.perf_counter() - t0)


def bench_many_nodes_tasks(target_nodes: int = 32, n: int = 500) -> float:
    """LEASE-PATH SMOKE, not a many-node benchmark: registers up to
    cores*4 simulated node processes ON ONE HOST and pushes n tasks
    through the head's lease machinery. The number is NOT comparable to
    the reference's many_nodes release benchmark (250 real nodes over a
    network) — it only guards the head's per-node bookkeeping cost from
    regressing. Node count is capped by host cores; simulated nodes carry
    fractional CPU."""
    import os as _os

    import ray_tpu as rt

    cluster = rt._internal_cluster()
    cores = _os.cpu_count() or 1
    extra = max(min(target_nodes, cores * 4) - len(cluster.nodes), 0)
    added = [cluster.add_node({"CPU": 1}) for _ in range(extra)]
    time.sleep(0.5)

    @rt.remote
    def noop():
        return None

    rt.get([noop.remote() for _ in range(50)])
    t0 = time.perf_counter()
    rt.get([noop.remote() for _ in range(n)])
    rate = _rate(n, time.perf_counter() - t0)
    for nh in added:
        # Graceful drain-then-terminate: a planned teardown must not spray
        # warning-level "node dead: connection lost" lines into the bench
        # tail (they read as failures and break tail parsing).
        cluster.remove_node(nh)
    return rate


def bench_many_actors(n: int = 1000) -> float:
    """Actor creation throughput at scale: create N cheap actors, wait for
    all to answer, kill them (reference:
    ``release/benchmarks/many_actors.json`` — 528.8 actors/s creating 10k
    actors across a cluster). Zero-CPU actors ride the node:slot marker so
    N isn't capped by cores."""
    @ray_tpu.remote(num_cpus=0)
    class A:
        def ping(self):
            return None

    t0 = time.perf_counter()
    actors = [A.remote() for _ in range(n)]
    ray_tpu.get([a.ping.remote() for a in actors])
    rate = _rate(n, time.perf_counter() - t0)
    for a in actors:
        ray_tpu.kill(a)
    return rate


def bench_many_pgs(n: int = 200) -> float:
    """Placement-group creation throughput: burst-create N single-bundle
    PGs, wait all ready, then remove (reference:
    ``release/benchmarks/many_pgs.json`` — 80.95 PGs/s). Rate covers
    create+ready; removal is off the clock like the reference."""
    from ray_tpu.util.placement_group import (
        placement_group,
        remove_placement_group,
    )

    t0 = time.perf_counter()
    pgs = [placement_group([{"CPU": 0.001}]) for _ in range(n)]
    for pg in pgs:
        pg.ready(timeout=60)
    rate = _rate(n, time.perf_counter() - t0)
    for pg in pgs:
        remove_placement_group(pg)
    return rate


def bench_queued_tasks(n: int = 1_000_000) -> float:
    """Seconds to submit-and-drain N queued noop tasks (reference:
    ``release/perf_metrics/scalability/single_node.json`` — 1M queued tasks
    in 140.07s). Returns elapsed SECONDS (lower is better), reported as
    ``queued_{n}_tasks_s``."""
    @ray_tpu.remote(num_cpus=0)
    def noop():
        return None

    ray_tpu.get([noop.remote() for _ in range(100)])  # warm
    t0 = time.perf_counter()
    refs = [noop.remote() for _ in range(n)]
    # Drain in windows: one get() holding N futures peaks memory; the
    # reference benchmark also consumes results incrementally.
    for i in range(0, n, 10_000):
        ray_tpu.get(refs[i : i + 10_000])
    return time.perf_counter() - t0


def _progress(name: str):
    import sys

    print(f"[bench] {name}...", file=sys.stderr, flush=True)


def run_core_benchmarks(quick: bool = False) -> Dict[str, float]:
    scale = 0.25 if quick else 1.0
    out = {}
    # Label which store the object-plane legs exercised: fallback-store
    # numbers are NOT comparable to the native-arena targets, and a silent
    # native-build failure must be visible in the recorded bench artifact.
    from ray_tpu import native as rt_native
    from ray_tpu._private import worker as worker_mod

    out["native_store_active"] = bool(
        worker_mod.get_global_worker().shm.native_enabled
    )
    store_err = rt_native.build_failure("librt_native.so")
    if not out["native_store_active"] and store_err is not None:
        raise RuntimeError(
            "refusing to bench: native store fell back because the native "
            "build FAILED (compile error):\n" + store_err
        )
    _progress("single_client_tasks_async")
    out["single_client_tasks_async_per_s"] = bench_single_client_tasks_async(
        int(2000 * scale)
    )
    _progress("single_client_tasks_sync")
    out["single_client_tasks_sync_per_s"] = bench_single_client_tasks_sync(
        int(300 * scale)
    )
    _progress("actor_calls_async")
    out["actor_calls_async_per_s"] = bench_actor_calls_async(
        int(2000 * scale)
    )
    _progress("actor_calls_sync")
    out["actor_calls_sync_per_s"] = bench_actor_calls_sync(int(300 * scale))
    _progress("actor_calls_1_n")
    out["actor_calls_1_n_per_s"] = bench_actor_calls_1_n(int(2000 * scale))
    _progress("actor_calls_concurrent")
    out["actor_calls_concurrent_per_s"] = bench_actor_calls_concurrent(
        int(1000 * scale)
    )
    _progress("async_actor_calls")
    out["async_actor_calls_per_s"] = bench_async_actor_calls(
        int(1000 * scale)
    )
    _progress("put_gigabytes")
    out["single_client_put_gb_per_s"] = bench_put_gigabytes(
        0.5 if quick else 2.0
    )
    try:
        _progress("put_get_device")
        out["put_get_device_gb_per_s"] = bench_put_get_device(
            0.125 if quick else 0.5
        )
    except Exception as e:
        # jax-less / device-less hosts record the miss, never sink the run
        out["put_get_device_error"] = f"{type(e).__name__}: {e}"
    _progress("get_calls")
    out["single_client_get_calls_per_s"] = bench_get_calls(
        int(2000 * scale)
    )
    _progress("put_calls")
    out["single_client_put_calls_per_s"] = bench_put_calls(
        int(1000 * scale)
    )
    _progress("get_10k_refs")
    out["get_10k_refs_per_s"] = bench_get_10k_refs(
        2000 if quick else 10_000
    )
    _progress("wait_1k_refs")
    out["wait_1k_refs_per_s"] = bench_wait_1k_refs(
        250 if quick else 1000
    )
    _progress("get_actor_refs")
    out["get_actor_refs_per_s"] = bench_get_actor_refs(
        250 if quick else 1000
    )
    # Let the 10k-refs/wait legs' free backlog drain: PG churn should
    # measure placement-group ops, not the previous leg's cleanup fanout
    # (observed 79/s mid-drain vs ~2,000/s steady on the same build).
    time.sleep(2.0)
    _progress("pg_churn")
    out["pg_create_remove_per_s"] = bench_pg_churn(20 if quick else 50)
    import os as _os

    cores = _os.cpu_count() or 1
    # Client count/size scale with the host: each client is a full driver
    # process (jax import and all) — 4 of them on a 1-core box time out
    # without measuring anything.
    clients = 2 if (quick or cores < 8) else 4
    mc_n = int(1000 * scale) if cores >= 4 else min(int(1000 * scale), 250)
    try:
        _progress("multi_client_tasks_async")
        out["multi_client_tasks_async_per_s"] = bench_multi_client_tasks_async(
            clients=clients, n=mc_n
        )
    except Exception as e:  # multi-process benches must not sink the run
        import logging

        logging.getLogger(__name__).warning("multi-client bench failed: %s", e)
    try:
        _progress("multi_client_put")
        out["multi_client_put_gb_per_s"] = bench_multi_client_put(
            clients=clients,
            total_mb=(200 if quick else 500) if cores >= 4 else 100,
        )
    except Exception as e:
        import logging

        logging.getLogger(__name__).warning("multi-client put failed: %s", e)
    try:
        _progress("actor_calls_n_n")
        out["actor_calls_n_n_per_s"] = bench_actor_calls_n_n(
            clients=clients, n=mc_n
        )
    except Exception as e:
        import logging

        logging.getLogger(__name__).warning("n_n actor bench failed: %s", e)
    try:
        _progress("many_nodes_tasks")
        # key says "smoke": one-host simulated nodes, NOT comparable to
        # the reference's 250-real-node many_nodes number (see docstring)
        out["many_nodes_lease_smoke_per_s"] = bench_many_nodes_tasks(
            8 if quick else 32, int(500 * scale)
        )
    except Exception as e:
        import logging

        logging.getLogger(__name__).warning("many-nodes bench failed: %s", e)
    # Scale envelope (reference: release/benchmarks/*.json +
    # scalability/single_node.json). Failures are recorded, not swallowed:
    # a missing number in the bench artifact hides a regression.
    for key, fn in (
        ("many_actors_per_s",
         lambda: bench_many_actors(200 if quick else 1000)),
        ("many_pgs_per_s", lambda: bench_many_pgs(50 if quick else 200)),
        ("queued_5k_tasks_s" if quick else "queued_1m_tasks_s",
         lambda: bench_queued_tasks(5_000 if quick else 1_000_000)),
    ):
        try:
            _progress(key)
            out[key] = fn()
        except Exception as e:
            out[key + "_error"] = f"{type(e).__name__}: {e}"
    return out
