"""Core microbenchmarks (reference analog: ``python/ray/_private/ray_perf.py``
run by ``release/microbenchmark/run_microbenchmark.py`` — same workload shapes
so numbers are directly comparable to BASELINE.md)."""
from __future__ import annotations

import time
from typing import Dict

import numpy as np

import ray_tpu


def _rate(n, t):
    return n / t if t > 0 else float("inf")


def bench_single_client_tasks_async(n: int = 2000) -> float:
    @ray_tpu.remote
    def noop():
        return None

    ray_tpu.get([noop.remote() for _ in range(50)])  # warm the lease path
    t0 = time.perf_counter()
    refs = [noop.remote() for _ in range(n)]
    ray_tpu.get(refs)
    return _rate(n, time.perf_counter() - t0)


def bench_single_client_tasks_sync(n: int = 300) -> float:
    @ray_tpu.remote
    def noop():
        return None

    ray_tpu.get(noop.remote())
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(noop.remote())
    return _rate(n, time.perf_counter() - t0)


def bench_actor_calls_async(n: int = 2000) -> float:
    @ray_tpu.remote
    class A:
        def m(self):
            return None

    a = A.remote()
    ray_tpu.get(a.m.remote())
    t0 = time.perf_counter()
    ray_tpu.get([a.m.remote() for _ in range(n)])
    return _rate(n, time.perf_counter() - t0)


def bench_actor_calls_sync(n: int = 300) -> float:
    @ray_tpu.remote
    class A:
        def m(self):
            return None

    a = A.remote()
    ray_tpu.get(a.m.remote())
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(a.m.remote())
    return _rate(n, time.perf_counter() - t0)


def bench_put_gigabytes(total_gb: float = 2.0) -> float:
    """Large-object put throughput (reference shape: ray_perf puts numpy
    arrays; zero-copy serialization means one memcpy into the arena). Refs
    drop as we go — sustained throughput recycles hot arena pages the way
    a training feed does."""
    chunk = np.random.rand(100 * 1024 * 1024 // 8)  # 100MB float64
    n = max(int(total_gb * 1024 / 100), 1)
    ref = ray_tpu.put(chunk)  # warm: arena creation + page faults
    del ref
    t0 = time.perf_counter()
    for _ in range(n):
        ref = ray_tpu.put(chunk)
        del ref
    dt = time.perf_counter() - t0
    return n * chunk.nbytes / (1024 ** 3) / dt


def bench_get_calls(n: int = 2000) -> float:
    ref = ray_tpu.put(np.zeros(1000, np.float64))  # ~8KB, memory-store path
    ray_tpu.get(ref)
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(ref)
    return _rate(n, time.perf_counter() - t0)


def run_core_benchmarks(quick: bool = False) -> Dict[str, float]:
    scale = 0.25 if quick else 1.0
    return {
        "single_client_tasks_async_per_s": bench_single_client_tasks_async(
            int(2000 * scale)
        ),
        "single_client_tasks_sync_per_s": bench_single_client_tasks_sync(
            int(300 * scale)
        ),
        "actor_calls_async_per_s": bench_actor_calls_async(int(2000 * scale)),
        "actor_calls_sync_per_s": bench_actor_calls_sync(int(300 * scale)),
        "single_client_put_gb_per_s": bench_put_gigabytes(0.5 if quick else 2.0),
        "single_client_get_calls_per_s": bench_get_calls(int(2000 * scale)),
    }
