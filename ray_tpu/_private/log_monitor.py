"""Worker log capture + streaming (the cluster's log plane).

Reference behavior reproduced (not code): ``python/ray/_private/
log_monitor.py`` tails each worker's redirected stdout/stderr files and
publishes new lines over GCS pubsub; ``python/ray/_private/worker.py:2285
print_worker_logs`` echoes them on the driver prefixed with
``(name pid=..., node=...)``. TPU-era shape: the process-per-host worker
redirects its OWN fds 1/2 into session-dir files (C-level writes from
native/XLA code land there too) and a daemon thread tails those files,
pushing deltas to the head over the existing RPC connection — no separate
monitor process per node.

Files live in ``{session_dir}/logs/worker-{node8}.{out,err}`` and survive
the worker, so ``rt logs`` and the dashboard can read history through the
head while the driver stream shows lines live.
"""
from __future__ import annotations

import os
import threading
import time
from typing import List, Optional, Tuple

# One publish is capped so a runaway print loop cannot wedge the head
# connection with multi-MB notifies; the tail just catches up next poll.
MAX_LINES_PER_PUBLISH = 200
MAX_LINE_LEN = 4096
POLL_S = 0.2


def session_log_dir(session_dir: str) -> str:
    d = os.path.join(session_dir, "logs")
    os.makedirs(d, exist_ok=True)
    return d


def redirect_stdio(session_dir: str, node_id: str) -> Tuple[str, str]:
    """Point fds 1/2 at per-worker session-dir files (dup2, so writes from
    C/native code are captured too — a Python-level sys.stdout swap would
    miss them). Returns the two paths. Line-buffered via O_APPEND +
    unbuffered fds; Python-side print() still buffers per line because
    sys.stdout is re-opened in line-buffered text mode."""
    import sys

    d = session_log_dir(session_dir)
    out_path = os.path.join(d, f"worker-{node_id[:8]}.out")
    err_path = os.path.join(d, f"worker-{node_id[:8]}.err")
    out_fd = os.open(out_path, os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
    err_fd = os.open(err_path, os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
    os.dup2(out_fd, 1)
    os.dup2(err_fd, 2)
    os.close(out_fd)
    os.close(err_fd)
    sys.stdout = os.fdopen(1, "w", buffering=1, errors="replace")
    sys.stderr = os.fdopen(2, "w", buffering=1, errors="replace")
    return out_path, err_path


class LogMonitor:
    """Daemon thread tailing this worker's redirected log files and
    publishing new complete lines to the head ("worker_logs" notifies).
    The head buffers them for ``rt logs``/dashboard and fans them out to
    subscribed drivers for the prefixed live echo."""

    def __init__(self, worker, paths: List[Tuple[str, str]]):
        # paths: [(stream_name, file_path)]
        self.worker = worker
        self.paths = [(s, p, [0]) for s, p in paths]  # [offset] is mutable
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="rt-logmon"
        )

    def start(self):
        self._thread.start()

    def stop(self):
        self._stop.set()

    def _run(self):
        while not self._stop.wait(POLL_S):
            for stream, path, off in self.paths:
                try:
                    self._poll_one(stream, path, off)
                except Exception:
                    pass  # the log plane must never kill a worker

    def _poll_one(self, stream: str, path: str, off: List[int]):
        try:
            size = os.path.getsize(path)
        except OSError:
            return
        if size <= off[0]:
            if size < off[0]:
                off[0] = 0  # truncated/rotated: restart from the top
            return
        with open(path, "rb") as f:
            f.seek(off[0])
            chunk = f.read(1 << 20)
        # publish only COMPLETE lines; the partial tail stays for next poll
        nl = chunk.rfind(b"\n")
        if nl < 0:
            if len(chunk) >= MAX_LINE_LEN:  # unterminated runaway line
                nl = len(chunk) - 1
            else:
                return
        raw_lines = chunk[: nl + 1].splitlines(keepends=True)
        w = self.worker
        for i in range(0, len(raw_lines), MAX_LINES_PER_PUBLISH):
            batch_raw = raw_lines[i : i + MAX_LINES_PER_PUBLISH]
            batch = [
                ln.rstrip(b"\r\n").decode("utf-8", "replace")[:MAX_LINE_LEN]
                for ln in batch_raw
            ]
            try:
                w.gcs.notify(
                    "worker_logs",
                    {
                        "node_id": w.node_id,
                        "pid": os.getpid(),
                        "job_id": w.job_id.hex() if w.job_id else "",
                        "stream": stream,
                        "lines": batch,
                    },
                )
            except Exception:
                # Head gone (restart / reconnect window): the offset only
                # moved past PUBLISHED batches, so these lines are re-read
                # and re-published once the connection is back.
                return
            off[0] += sum(len(ln) for ln in batch_raw)


def print_worker_logs(data: dict, file=None) -> None:
    """Driver-side echo of a worker_logs pubsub message, prefixed the way
    the reference prints remote output: ``(worker pid=..., node=...)``."""
    import sys

    out = file or (
        sys.stderr if data.get("stream") == "stderr" else sys.stdout
    )
    prefix = f"(worker pid={data.get('pid')}, node={str(data.get('node_id'))[:8]})"
    try:
        for line in data.get("lines", ()):
            print(f"{prefix} {line}", file=out)
        out.flush()
    except Exception:
        pass
