"""Device-plane object store: first-class ``jax.Array`` objects.

The framework exists for device workloads, yet until this plane every
``jax.Array`` crossing put/get was staged device→host by cloudpickle's
``__reduce__`` and shipped over the host ring — the one workload class a
TPU-native runtime is for paid the full host-serialization tax. Reference
shape: the plasma store holds payload bytes while the owner-resolved
directory holds locations; here the "payload" never leaves the device —
only structured metadata crosses the control plane.

Contract (SURVEY.md §object-store; ROADMAP "Device-plane object store"):

- **put()** of a top-level ``jax.Array`` registers a directory entry
  carrying ``{dtype, shape, sharding spec, placement (per-shard
  device/node), nbytes}`` through the same ordered ref-op path host
  objects use (``worker._register_object_async`` → ``object_register``,
  memtrack ``kind="device"``). Bytes stay on device in the owner's
  per-process device table; zero cloudpickle of the payload.
- **get()** resolves locally first: the owner (or a caching consumer)
  answers from its device table — for same-slice peers a reshard is a
  ``jax.device_put`` riding ICI, no host staging. A cross-process/
  cross-slice consumer pulls per-shard HOST buffers from the owner over
  ONE ``pull_device_shards`` RPC (the DCN leg), reassembles, and
  materializes a ``jax.Array`` with the consumer's layout —
  producer-equivalent by default, or any requested ``NamedSharding`` via
  :func:`get_array`/:func:`reshard`.
- **Host fallback**: with JAX absent or the consumer on a different
  platform than the producer, get() returns the assembled numpy array —
  so ``JAX_PLATFORMS=cpu`` tier-1 exercises the full wire protocol.
- **Gate**: ``rt_config.device_objects`` (``RT_DEVICE_OBJECTS``), default
  ON and effective only when JAX is importable; disabled, the host
  cloudpickle path is byte-identical to the pre-plane behavior.

Fault points (chaos matrix): ``devstore.register`` (directory
registration is an *optimization* — on error/drop the reader falls back
to pull-from-owner, which the owner can always serve),
``devstore.shard_pull`` (consumer retries against the owner with jittered
backoff; a drop behaves like a lost reply and re-arms — never a hang,
never a half-materialized array), ``devstore.reshard``.
"""
from __future__ import annotations

import asyncio
import logging
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)


# --------------------------------------------------------------- gating

def enabled() -> bool:
    """Device plane on? Config gate AND jax importable (a process that
    never imported jax cannot be holding a device array to route)."""
    try:
        from ray_tpu._private.config import rt_config

        if not bool(rt_config.device_objects):
            return False
    except Exception as e:  # config bootstrap orders vary in tools
        logger.debug("device_objects config unavailable: %s", e)
    return sys.modules.get("jax") is not None


def is_device_array(value: Any) -> bool:
    """True for a *concrete* ``jax.Array`` (tracers stay with the normal
    serializer — a traced put is user error the host path reports).
    getattr-guarded: callers can run while jax itself is mid-import."""
    jax_mod = sys.modules.get("jax")
    jax_array = getattr(jax_mod, "Array", None)
    if jax_array is None or not isinstance(value, jax_array):
        return False
    tracer = getattr(getattr(jax_mod, "core", None), "Tracer", None)
    return tracer is None or not isinstance(value, tracer)


def is_device_meta(meta: Any) -> bool:
    """Directory/store metadata describing a device-plane object."""
    return isinstance(meta, dict) and "device" in meta


# ------------------------------------------------- host-staging ledger

_staged_lock = threading.Lock()
_host_staged = {"count": 0, "bytes": 0}


def note_host_staged(value: Any) -> None:
    """A device array went through HOST serialization anyway (plane off,
    or nested inside a container put/task arg): record the staged bytes
    so the memory plane can attribute host rows that are really device
    payloads instead of double-counting them as host-born data."""
    try:
        nbytes = int(value.nbytes)
    except (AttributeError, TypeError):
        nbytes = 0
    with _staged_lock:
        _host_staged["count"] += 1
        _host_staged["bytes"] += nbytes


def host_staged_stats() -> Dict[str, int]:
    with _staged_lock:
        return dict(_host_staged)


# ----------------------------------------------------------- metadata

def _index_to_wire(index: Tuple, shape: Tuple[int, ...]) -> List[List[int]]:
    """Per-shard global-index slices → [[start, stop], ...] (step-1 only,
    which is what shard indices are)."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = int(dim) if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _describe_sharding(arr) -> Dict[str, Any]:
    from jax.sharding import NamedSharding, SingleDeviceSharding

    sh = arr.sharding
    if isinstance(sh, SingleDeviceSharding):
        return {"type": "single"}
    if isinstance(sh, NamedSharding):
        spec = []
        for p in tuple(sh.spec):
            if p is None:
                spec.append(None)
            elif isinstance(p, tuple):
                spec.append([str(a) for a in p])
            else:
                spec.append([str(p)])
        return {
            "type": "named",
            "axes": [
                [str(name), int(size)]
                for name, size in zip(sh.mesh.axis_names, sh.mesh.devices.shape)
            ],
            "spec": spec,
        }
    # GSPMD/positional/etc: consumers fall back to a single-device (or
    # host) materialization; the placement list still pins correctness.
    return {"type": "other", "repr": repr(sh)[:160]}


def describe(arr, node_id: Optional[str] = None) -> Dict[str, Any]:
    """Structured directory metadata for a device array. This is the
    PINNED device-metadata schema (PARITY.md Round-14): payload bytes are
    deliberately absent — the directory knows shape/layout/placement,
    never data."""
    placement = []
    for i, s in enumerate(arr.addressable_shards):
        placement.append({
            "shard": i,
            "device": int(getattr(s.device, "id", 0)),
            "node": node_id,
            "index": _index_to_wire(s.index, arr.shape),
        })
    devs = list(arr.devices())
    return {
        "dtype": str(arr.dtype),
        "shape": [int(d) for d in arr.shape],
        "nbytes": int(arr.nbytes),
        "platform": devs[0].platform if devs else "cpu",
        "sharding": _describe_sharding(arr),
        "placement": placement,
    }


def _sharding_from_spec(spec: Dict[str, Any], jax_mod):
    """Rebuild a producer-equivalent NamedSharding on THIS process's
    devices, or None when the layout can't be reproduced locally (fewer
    devices, non-named sharding) — callers then materialize single-device."""
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    sh = spec.get("sharding") or {}
    if sh.get("type") != "named":
        return None
    axes = sh.get("axes") or []
    n = 1
    for _, size in axes:
        n *= int(size)
    devs = jax_mod.devices()
    if n == 0 or n > len(devs):
        return None
    mesh = Mesh(
        np.array(devs[:n]).reshape([int(size) for _, size in axes]),
        tuple(str(name) for name, _ in axes),
    )
    parts = []
    for p in sh.get("spec") or ():
        if p is None:
            parts.append(None)
        else:
            parts.append(p[0] if len(p) == 1 else tuple(p))
    return NamedSharding(mesh, PartitionSpec(*parts))


# ------------------------------------------------------------ put path

def put_device(worker, value) -> Any:
    """Store a device array as a first-class object: metadata to the
    directory, bytes pinned on device in the owner's table. Mirrors
    worker.put()'s ordering contract — ownership records only after the
    store succeeded, registration rides the ordered ref-op queue so a
    free can never overtake it."""
    from ray_tpu._private import faultpoints
    from ray_tpu.object_ref import ObjectRef

    oid = worker._next_put_id()
    hex_ = oid.hex()
    spec = describe(value, node_id=worker.node_id)
    worker._device_objects[hex_] = value
    worker._register_owned(hex_)
    worker.memory_store[hex_] = ("dev", spec)
    worker._signal_store_event(hex_)
    meta = worker._with_xfer({
        "device": spec,
        "size": int(spec["nbytes"]),
        "node": worker.node_id,
        "owner": list(worker.addr or ()),
    })
    register = True
    if faultpoints.ACTIVE:
        try:
            if faultpoints.fire("devstore.register") == "drop":
                register = False
        except ConnectionError as e:
            # Registration is an optimization: a directory miss degrades
            # readers to pull-from-owner, which we can always serve.
            logger.debug("device-object registration for %s failed: %s",
                         hex_[:12], e)
            register = False
    if register:
        worker._register_object_async(hex_, meta)
    return ObjectRef(oid, tuple(worker.addr))


# ------------------------------------------------------- owner serving

def pack_shards(value) -> Tuple[List[dict], List[bytes]]:
    """Owner-side wire form: one host buffer per addressable shard plus
    its global index, so any consumer can reassemble without knowing the
    producer's mesh. Device→host copies happen HERE, per shard, only when
    a remote consumer actually pulls."""
    import numpy as np

    shards: List[dict] = []
    frames: List[Any] = []

    def add(host: np.ndarray, index):
        shards.append({
            "dtype": str(host.dtype),
            "shape": [int(d) for d in host.shape],
            "index": index,
        })
        # memoryview, not tobytes(): the wire encoder copies exactly once
        # into the socket buffer — a bytes() here would double that. The
        # ndarray stays referenced via the view until the reply is sent.
        frames.append(memoryview(host).cast("B"))

    if is_device_array(value):
        shape = value.shape
        for s in value.addressable_shards:
            add(np.ascontiguousarray(s.data),
                _index_to_wire(s.index, shape))
    else:  # host-fallback value cached in the table
        add(np.ascontiguousarray(np.asarray(value)), None)
    return shards, frames


def assemble(spec: Dict[str, Any], shards: List[dict],
             frames: List[Any]):
    """Consumer-side reassembly of pulled shard buffers into ONE host
    ndarray in global shape. Pure function; runs on an executor thread
    (multi-MB memcpys must not block the event loop)."""
    import numpy as np

    shape = tuple(spec["shape"])
    if len(shards) == 1 and tuple(shards[0]["shape"]) == shape:
        # Single shard covering the whole value (single-device, replicated
        # or host-fallback producer): the received buffer IS the array —
        # zero-copy view instead of an alloc + memcpy.
        return np.frombuffer(
            frames[0], dtype=np.dtype(shards[0]["dtype"])
        ).reshape(shape)
    out = np.empty(shape, dtype=np.dtype(spec["dtype"]))
    for sh, buf in zip(shards, frames):
        piece = np.frombuffer(
            buf, dtype=np.dtype(sh["dtype"])
        ).reshape(tuple(sh["shape"]))
        idx = sh.get("index")
        if idx is None:
            out[...] = piece
        else:
            out[tuple(slice(a, b) for a, b in idx)] = piece
    return out


# ----------------------------------------------------------- get path

async def _pull_shards(worker, hex_: str, owner: Tuple, deadline):
    """One RPC pulls every shard the owner holds (O(owners) economics,
    like pull_object_batch). Re-armed long-poll + jittered retries mirror
    worker._pull_from_owner: a dropped reply behaves like the attempt
    deadline expiring, transient transport failures and typed retryable
    (code="unavailable") handler errors re-issue against the owner, and a
    persistent failure surfaces ObjectLostError — never a hang, never a
    partially-applied materialization (assembly happens only after a
    complete reply)."""
    from ray_tpu import exceptions as exc
    from ray_tpu._private import faultpoints, protocol
    from ray_tpu._private.backoff import Backoff
    from ray_tpu._private.config import rt_config

    if not owner:
        raise exc.ObjectLostError(hex_, "device object has no owner address")
    attempt_s = float(rt_config.rpc_deadline_s)
    retry = Backoff(base=0.05, cap=1.0)
    failures = 0
    max_failures = int(rt_config.rpc_retries)
    while True:
        try:
            if faultpoints.ACTIVE:
                fired = await faultpoints.async_fire("devstore.shard_pull")
                if fired == "drop":
                    # Reply lost in transit: exactly the attempt-deadline
                    # expiring.
                    raise asyncio.TimeoutError()
            conn = await worker.get_peer(owner)
            tmo = attempt_s
            if deadline is not None:
                tmo = min(tmo, max(deadline - time.monotonic(), 0))
            hh, frames = await asyncio.wait_for(
                conn.call("pull_device_shards", {"oid": hex_}), tmo
            )
            return hh, frames
        except asyncio.TimeoutError:
            if deadline is not None and time.monotonic() >= deadline:
                raise exc.GetTimeoutError(
                    f"get() timed out pulling device shards of {hex_}"
                )
            await asyncio.sleep(retry.next_delay())
        except (protocol.ConnectionLost, ConnectionRefusedError,
                OSError) as e:
            failures += 1
            if failures > max_failures:
                raise exc.ObjectLostError(
                    hex_, f"device-object owner unreachable ({e})"
                )
            if deadline is not None and time.monotonic() >= deadline:
                raise exc.GetTimeoutError(
                    f"get() timed out pulling device shards of {hex_}"
                )
            await asyncio.sleep(retry.next_delay())
        except protocol.RpcError as e:
            if getattr(e, "code", None) == "unavailable":
                # Typed retryable failure at the owner (injected or
                # transient): retry against the owner, bounded.
                failures += 1
                if failures > max_failures:
                    raise exc.ObjectLostError(
                        hex_, f"device shard pull kept failing ({e})"
                    )
                await asyncio.sleep(retry.next_delay())
                continue
            raise exc.ObjectLostError(hex_, str(e))


def _host_to_device(np_value, spec: Dict[str, Any]):
    """Materialize a pulled host array on THIS process's devices with a
    producer-equivalent layout. Host fallback (plain ndarray) when JAX is
    absent or the local platform differs from the producer's."""
    try:
        import jax as jax_mod
    except ImportError:
        return np_value
    try:
        if spec.get("platform") and jax_mod.default_backend() != spec["platform"]:
            return np_value
        target = _sharding_from_spec(spec, jax_mod)
        if target is None:
            return jax_mod.device_put(np_value)
        return jax_mod.device_put(np_value, target)
    except Exception as e:
        # A local mesh/layout problem must degrade to the host value the
        # protocol already delivered, not fail the get().
        logger.debug("device materialization fell back to host for "
                     "%s-shaped %s: %s", spec.get("shape"),
                     spec.get("dtype"), e)
        return np_value


async def materialize(worker, hex_: str, meta: Any, ref, deadline):
    """Resolve a device-plane object for THIS process.

    Local table hit (owner, or a consumer that already pulled): the array
    is returned as-is — for a same-slice peer wanting another layout,
    :func:`reshard` is a pure ``jax.device_put`` over ICI. Otherwise pull
    the shard buffers from the owner (the DCN leg), reassemble off-loop,
    land them on local devices, and cache."""
    value = worker._device_objects.get(hex_)
    if value is not None:
        return value
    spec = (meta or {}).get("device") if is_device_meta(meta) else meta
    owner: Tuple = ()
    if isinstance(meta, dict):
        owner = tuple(meta.get("owner") or ())
    if not owner:
        owner = tuple(getattr(ref, "owner_address", None) or ())
    hh, frames = await _pull_shards(worker, hex_, owner, deadline)
    spec = hh.get("spec") or spec or {}
    loop = asyncio.get_running_loop()
    np_value = await loop.run_in_executor(
        None, assemble, spec, hh.get("shards") or [], frames
    )
    value = await loop.run_in_executor(None, _host_to_device, np_value, spec)
    # Cache: repeated gets resolve locally (and serve further consumers
    # via the direct path); the owner's object_free fan-out evicts this.
    worker._device_objects[hex_] = value
    worker.memory_store[hex_] = ("dev", dict(spec))
    return value


def reshard(value, sharding):
    """Re-lay a device value to the CONSUMER's requested sharding (a pure
    ``jax.device_put`` — ICI traffic on a slice, never host staging).
    No-op for host-fallback values or ``sharding=None``."""
    if sharding is None:
        return value
    if not is_device_array(value):
        # Host-fallback value with a device request: land it now if a
        # local jax exists (covers numpy ground-truth tests).
        jax_mod = sys.modules.get("jax")
        if jax_mod is None:
            return value
        return jax_mod.device_put(value, sharding)
    from ray_tpu._private import faultpoints
    from ray_tpu._private.backoff import Backoff

    jax_mod = sys.modules["jax"]
    retry = Backoff(base=0.01, cap=0.2)
    attempts = 0
    while True:
        try:
            if faultpoints.ACTIVE:
                faultpoints.fire("devstore.reshard")
            return jax_mod.device_put(value, sharding)
        except ConnectionError as e:
            # Injected/transient unavailability: bounded jittered retry;
            # anything else (a real layout error) propagates typed.
            if getattr(e, "code", None) != "unavailable" or attempts >= 3:
                raise
            attempts += 1
            time.sleep(retry.next_delay())


def get_array(ref, sharding=None, timeout: Optional[float] = None):
    """``get()`` a device-plane object and materialize it with the
    consumer's requested sharding (the public resharding surface)."""
    from ray_tpu._private.worker import get_global_worker

    value = get_global_worker().get(ref, timeout=timeout)
    return reshard(value, sharding)
