"""Serialization: cloudpickle + zero-copy buffers + ObjectRef tracking.

TPU-native analog of the reference serialization layer (reference:
``python/ray/_private/serialization.py`` and vendored cloudpickle). Differences:

- We use the *installed* cloudpickle (the reference vendors its own).
- Zero-copy path: numpy arrays and ``jax.Array`` host buffers are serialized
  out-of-band via pickle protocol 5 buffer callbacks, so a put into the
  shared-memory store writes payload bytes exactly once.
- ``jax.Array`` values are staged device→host at serialization time: in a
  multi-controller SPMD world the *addressable* shards are what a host can
  legally own (the reference's CUDA tensor paths have no TPU analog; see
  SURVEY.md §5 "Distributed communication backend").
- ObjectRefs found inside values are recorded so the ownership layer can
  track borrows (reference: ``reference_counter.h`` borrowing).
"""
from __future__ import annotations

import logging
import pickle
import sys
from dataclasses import dataclass
from typing import Any, Callable, List, Optional

import cloudpickle

logger = logging.getLogger(__name__)

try:
    import numpy as _np
except ImportError:  # pragma: no cover - numpy is a hard dep in practice
    _np = None


@dataclass
class SerializedObject:
    """A serialized value: metadata + in-band pickle bytes + out-of-band buffers."""

    metadata: bytes
    inband: bytes
    buffers: List[pickle.PickleBuffer]
    contained_refs: List[Any]

    def total_bytes(self) -> int:
        n = len(self.inband)
        for b in self.buffers:
            n += b.raw().nbytes
        return n

    def to_frames(self, copy: bool = True) -> List[bytes]:
        """Flatten to a frame list: [metadata, inband, buf0, buf1, ...].

        The default COPIES out-of-band buffers: frames routinely outlive the
        call while the caller still owns (and may mutate) the source — e.g.
        task args queued for dispatch must be a snapshot from .remote() time.
        Pass copy=False only where the frames are consumed immediately and
        exactly once (the large-object put path writing straight into shm),
        which is where the zero-copy win lives.
        """
        bufs = [
            bytes(b.raw()) if copy else b.raw() for b in self.buffers
        ]
        return [self.metadata, self.inband] + bufs


METADATA_PICKLE5 = b"pickle5"
METADATA_RAW = b"raw"  # payload is a single raw bytes buffer


class DeviceObjectIntercept(Exception):
    """Control-flow signal: a top-level device array must be routed to the
    device-plane object store (``_private/devstore.py``) instead of host
    pickling. Raised only when the caller opted in (``allow_device=True``
    — worker.put does) and the device-objects plane is enabled; carries
    the value so the catcher can hand it to ``devstore.put_device``."""

    def __init__(self, value: Any):
        super().__init__("device array routed to devstore")
        self.value = value


def _stage_jax_arrays(value: Any, allow_device: bool = False) -> Any:
    """Interception point for device arrays entering host serialization.

    With the device-objects plane enabled and the caller opted in
    (worker.put), a top-level ``jax.Array`` never reaches cloudpickle:
    :class:`DeviceObjectIntercept` routes it to the devstore and the
    payload bytes stay on device. A top-level device array that stays on
    the host path (plane off, or a non-put serialization like a task
    return) is host-staged by ``jax.Array.__reduce__`` as before — but
    the staged bytes are RECORDED (``devstore.note_host_staged``) so the
    memory plane can attribute host rows that are really device payloads
    instead of double-counting them. Arrays nested inside containers ride
    cloudpickle wholesale, below this interception point.
    """
    jax_mod = sys.modules.get("jax")
    # getattr guard: serialize can run WHILE jax itself is importing
    # (sys.modules holds a partially initialized module then).
    jax_array = getattr(jax_mod, "Array", None)
    if jax_array is None or not isinstance(value, jax_array):
        return value
    from ray_tpu._private import devstore

    if allow_device and devstore.enabled() and devstore.is_device_array(value):
        raise DeviceObjectIntercept(value)
    devstore.note_host_staged(value)
    return value


class SerializationContext:
    """Serialize/deserialize values for the object store and the wire.

    ``ref_pickler``/``ref_unpickler`` are hooks the worker installs so that
    ObjectRefs embedded in values are converted to a plain descriptor on the
    way out (and counted as borrows on the way in).
    """

    def __init__(
        self,
        ref_pickler: Optional[Callable[[Any], tuple]] = None,
        ref_unpickler: Optional[Callable[[tuple], Any]] = None,
    ):
        self.ref_pickler = ref_pickler
        self.ref_unpickler = ref_unpickler

    def serialize(self, value: Any,
                  allow_device: bool = False) -> SerializedObject:
        if isinstance(value, bytes):
            # Fast path: raw bytes stored as a single out-of-band buffer.
            return SerializedObject(
                METADATA_RAW, b"", [pickle.PickleBuffer(value)], []
            )
        buffers: List[pickle.PickleBuffer] = []
        contained: List[Any] = []

        def buffer_cb(buf: pickle.PickleBuffer):
            buffers.append(buf)
            return False  # out-of-band

        if _np is not None and type(value) is _np.ndarray \
                and not value.dtype.hasobject:
            # Fast path: a plain non-object ndarray cannot contain
            # ObjectRefs or __main__-defined types, so the C pickler is
            # safe — and ~3x faster than cloudpickle's pure-Python
            # pickler. The wire format is identical (protocol-5 pickle
            # with out-of-band buffers), so deserialize is unchanged.
            inband = pickle.dumps(
                value, protocol=5, buffer_callback=buffer_cb
            )
            return SerializedObject(METADATA_PICKLE5, inband, buffers, [])
        value = _stage_jax_arrays(value, allow_device=allow_device)
        inband = cloudpickle.dumps(value, protocol=5, buffer_callback=buffer_cb)
        return SerializedObject(METADATA_PICKLE5, inband, buffers, contained)

    def deserialize(self, metadata: bytes, inband: bytes, buffers: List[Any]) -> Any:
        if metadata == METADATA_RAW:
            return bytes(buffers[0]) if not isinstance(buffers[0], bytes) else buffers[0]
        from ray_tpu.object_ref import ObjectRef, _deserialization_sink

        batch_hook = ObjectRef._deserialize_batch_hook
        if batch_hook is None:
            return pickle.loads(inband, buffers=buffers)
        # Collect nested refs during the load and register their borrows in
        # ONE batch-hook call: per-ref hook dispatch dominates deserializing
        # ref-dense containers (the get-10k-refs shape), and the batch lets
        # the worker move hex/owner bookkeeping off the calling thread.
        refs: List[Any] = []
        token = _deserialization_sink.set(refs)
        try:
            value = pickle.loads(inband, buffers=buffers)
        finally:
            _deserialization_sink.reset(token)
            # Register INSIDE the finally: a loads() that raises mid-value
            # has already materialized (and interned) the earlier refs —
            # their GC-time decrements need the matching borrow, and a
            # later deserialize of the same id aliases the cached ref on
            # the assumption its borrow was registered.
            if refs:
                try:
                    batch_hook(refs)
                except Exception as e:
                    # A dropped borrow registration risks a premature free
                    # at the owner — the exact class of silent failure
                    # the memtrack leak detector exists to surface; never
                    # swallow it without a trace.
                    logger.debug(
                        "batched borrow registration for %d ref(s) "
                        "failed: %s", len(refs), e,
                    )
        return value

    def deserialize_frames(self, frames: List[bytes]) -> Any:
        return self.deserialize(frames[0], frames[1], frames[2:])
