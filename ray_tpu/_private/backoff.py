"""Jittered, capped exponential backoff for retry/poll loops.

Every fixed-period ``time.sleep(K)`` retry loop in a distributed system
synchronizes its contenders: N workers that lost the same race all wake
on the same tick and hammer the head again (reference: the exponential
backoff helpers scattered through ray's GCS client reconnect paths). The
lint rule RT204 flags constant sleeps in loops; this is the sanctioned
replacement.

Usage::

    poll = Backoff(base=0.5, cap=4.0)
    while not done():
        poll.sleep()          # 0.5, 1, 2, 4, 4, ... (each +/- jitter)
        if made_progress():
            poll.reset()      # back to the fast tick
"""
from __future__ import annotations

import random
import time


class Backoff:
    """Exponential backoff with full-spread jitter.

    The n-th delay is ``min(cap, base * factor**n)`` scaled uniformly
    into ``[1 - jitter, 1]`` of itself, so contenders decorrelate instead
    of waking in lockstep.
    """

    def __init__(self, base: float = 0.1, cap: float = 5.0,
                 factor: float = 2.0, jitter: float = 0.5,
                 rand=random.random, sleep=time.sleep):
        if base <= 0 or cap < base or factor < 1 or not 0 <= jitter <= 1:
            raise ValueError(
                f"invalid backoff: base={base}, cap={cap}, factor={factor}, "
                f"jitter={jitter}"
            )
        self.base = base
        self.cap = cap
        self.factor = factor
        self.jitter = jitter
        self._rand = rand
        self._sleep = sleep
        self._attempt = 0

    def next_delay(self) -> float:
        d = min(self.cap, self.base * self.factor ** self._attempt)
        if d < self.cap:
            # Stop growing the exponent once capped: factor**n overflows
            # to OverflowError after ~1k attempts, which would kill
            # long-lived poll loops (e.g. the pressure killer thread).
            self._attempt += 1
        return d * (1.0 - self.jitter * self._rand())

    def sleep(self) -> float:
        """Sleep for the next delay; returns the delay actually used."""
        d = self.next_delay()
        self._sleep(d)
        return d

    def reset(self):
        """Progress was made: drop back to the fast tick."""
        self._attempt = 0
