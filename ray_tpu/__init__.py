"""ray_tpu: a TPU-native distributed compute framework.

Capability-compatible with the reference engine (tasks, actors, objects,
placement groups, collectives, Train/Tune/Data/Serve layers — see SURVEY.md),
re-designed for TPU pods: SPMD-first data plane (jax/XLA over ICI), thin
control plane over DCN, process-per-host workers, typed TPU slice resources.

Public API (reference: ``python/ray/_private/worker.py`` exports):
    init, shutdown, remote, get, put, wait, kill, cancel, get_actor,
    get_runtime_context, cluster_resources, available_resources, nodes
"""
from __future__ import annotations

import atexit
import inspect
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu import exceptions
from ray_tpu._private import worker as _worker_mod
from ray_tpu._private.gcs import HeadService
from ray_tpu._private.ids import JobID
from ray_tpu._private.node import LocalCluster, spawn_node
from ray_tpu._private.worker import CoreWorker, get_global_worker
from ray_tpu.actor import ActorClass, ActorHandle, exit_actor, method
from ray_tpu.object_ref import ObjectRef
from ray_tpu.remote_function import RemoteFunction
from ray_tpu.runtime_context import get_runtime_context

__version__ = "0.1.0"

__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "get",
    "put",
    "wait",
    "kill",
    "cancel",
    "get_actor",
    "exit_actor",
    "method",
    "get_runtime_context",
    "cluster_resources",
    "available_resources",
    "nodes",
    "ObjectRef",
    "ActorHandle",
    "exceptions",
    "__version__",
]

_init_lock = threading.Lock()
_cluster: Optional[LocalCluster] = None
_head: Optional[HeadService] = None
# True when THIS process minted/adopted RT_AUTH_TOKEN (cleared on shutdown)
_token_set_by_init = False


def is_initialized() -> bool:
    return _worker_mod.global_worker is not None


def _prune_old_sessions(keep: int, active: str):
    """Bound /tmp/ray_tpu growth: session dirs (worker log files) from old
    clusters are removed oldest-first beyond the newest ``keep`` (the
    reference bounds its session dirs the same way — session_latest
    rotation). Best-effort; never blocks startup."""
    import shutil

    def liveness(d: str) -> float:
        """Newest mtime across the session dir and its log files — a LIVE
        cluster keeps appending, so its files stay recent even though the
        dir's own mtime froze at creation."""
        newest = os.path.getmtime(d)
        logs = os.path.join(d, "logs")
        try:
            for f in os.listdir(logs):
                newest = max(newest, os.path.getmtime(os.path.join(logs, f)))
        except OSError:
            pass
        return newest

    try:
        root = "/tmp/ray_tpu"
        dirs = [
            os.path.join(root, d) for d in os.listdir(root)
            if d.startswith("session_")
        ]
        dirs = [d for d in dirs if os.path.abspath(d) != os.path.abspath(active)]
        dirs.sort(key=liveness)
        cutoff = time.time() - 3600
        for d in dirs[: max(len(dirs) - (keep - 1), 0)]:
            if liveness(d) < cutoff:  # never rmtree a live cluster's logs
                shutil.rmtree(d, ignore_errors=True)
    except OSError:
        pass


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[int] = None,
    num_nodes: int = 1,
    resources: Optional[Dict[str, float]] = None,
    labels: Optional[Dict[str, str]] = None,
    namespace: str = "default",
    ignore_reinit_error: bool = False,
    log_level: str = "WARNING",
    _node_env: Optional[Dict[str, str]] = None,
    _system_config: Optional[Dict[str, Any]] = None,
) -> "ClientContext":
    """Start (or connect to) a cluster.

    - No address: starts a head service in-process plus ``num_nodes`` node
      processes, each with ``num_cpus`` CPUs (default: host cpu count) and any
      extra ``resources`` (e.g. {"TPU": 4}).
    - ``address="host:port"``: connect this driver to an existing head.

    Reference analog: ``ray.init`` (``python/ray/_private/worker.py:1413``).
    """
    global _cluster, _head, _token_set_by_init
    with _init_lock:
        if _worker_mod.global_worker is not None:
            if ignore_reinit_error:
                return ClientContext(_worker_mod.global_worker)
            raise RuntimeError("ray_tpu.init() called twice")
        try:
            return _init_locked(
                address, num_cpus, num_nodes, resources, labels,
                _node_env, _system_config,
            )
        except BaseException:
            # A failed start (node registration timeout, port in use, ...)
            # must not leave half a cluster behind: the NEXT init would
            # die on 'called twice' and every later caller cascades.
            _cleanup_failed_init()
            raise


def _teardown_globals():
    """The ONE teardown path (shutdown() and failed-init cleanup both use
    it — two copies would drift): best-effort, tolerant of half-started
    state in any field."""
    global _cluster, _head, _token_set_by_init
    if _cluster is not None:
        try:
            _cluster.shutdown()
        except Exception:
            pass
        _cluster = None
    w = _worker_mod.global_worker
    if w is not None:
        try:
            w.shutdown()
        except Exception:
            pass
    _worker_mod.global_worker = None
    _head = None
    if _token_set_by_init:
        # A token THIS process minted/adopted dies with the cluster: a
        # later init against a different head must not present it (the
        # rejection is an opaque ConnectionLost). User-provided tokens
        # are left alone.
        os.environ.pop("RT_AUTH_TOKEN", None)
        _token_set_by_init = False


def _cleanup_failed_init():
    _teardown_globals()


def _init_locked(address, num_cpus, num_nodes, resources, labels,
                 _node_env, _system_config):
    global _cluster, _head, _token_set_by_init
    if _system_config:
        # Cluster-wide config overrides (reference: _system_config on
        # the raylet/GCS command line, gcs_server.h:72). Installed here
        # for the driver + in-process head; propagated to spawned
        # nodes as RT_* env vars via _node_env below, and published to
        # the head KV so workers that CONNECT later (remote clusters,
        # head-restart rejoin) apply them at registration.
        from ray_tpu._private.config import rt_config

        rt_config.apply_system_config(_system_config)
        _node_env = dict(
            rt_config.system_config_env(), **(_node_env or {})
        )
        if "memtrack_enabled" in _system_config:
            # The gate resolves at module import (before this apply):
            # re-sync so a driver-side _system_config toggle takes
            # effect in THIS process too, not just in spawned nodes.
            from ray_tpu._private import memtrack

            (memtrack.enable if rt_config.memtrack_enabled
             else memtrack.disable)()
    # Resolve the head address like the reference's RAY_ADDRESS/"auto":
    # env var (set for submitted jobs), then the head's address file.
    if address is None:
        address = os.environ.get("RAY_TPU_ADDRESS")
    if address == "auto":
        from ray_tpu._private.head_main import read_address_file

        info = read_address_file()
        if info is None:
            raise ConnectionError(
                "address='auto' but no running head found "
                "(start one with `raytpu start --head`)"
            )
        address = info["address"]
        from ray_tpu._private import auth as _auth

        if _auth.adopt_token(info):
            _token_set_by_init = True
    job_id = JobID.from_random()
    if address is None:
        # Session dir: per-cluster scratch for worker log files (and
        # anything else session-scoped). Spawned nodes learn it via
        # RT_SESSION_DIR (reference: the ray session_latest dir).
        session_dir = os.environ.get("RT_SESSION_DIR")
        if not session_dir:
            session_dir = os.path.join(
                "/tmp/ray_tpu",
                f"session_{int(time.time())}_{os.getpid()}",
            )
        os.makedirs(session_dir, exist_ok=True)
        _prune_old_sessions(keep=5, active=session_dir)
        # Cluster auth token (reference: src/ray/rpc/authentication/):
        # minted per cluster; spawned nodes inherit it via the env and
        # every TCP plane requires it as the connection's first
        # message. RT_AUTH_TOKEN= (empty) disables.
        from ray_tpu._private import auth as _auth

        if _auth.ensure_cluster_token():
            _token_set_by_init = True
        _node_env = dict(_node_env or {}, RT_SESSION_DIR=session_dir)
        head = HeadService()
        driver = CoreWorker(
            is_driver=True,
            gcs_addr=("127.0.0.1", 0),  # patched after head start
            job_id=job_id,
            head=head,
        )
        # Globals are assigned BEFORE boot so a mid-boot failure (e.g. the
        # ready-wait timeout) gives the cleanup path something to tear
        # down — otherwise the core-loop thread + head would leak.
        _worker_mod.global_worker = driver
        _head = head
        # Start head + driver service on one core loop.
        ready = threading.Event()
        boot_err: List[BaseException] = []

        def runner():
            import asyncio

            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            driver.loop = loop

            async def boot():
                addr = await head.start()
                driver.gcs_addr = addr
                await driver._async_setup()

            try:
                loop.run_until_complete(boot())
            except BaseException as e:  # surface boot failures to caller
                boot_err.append(e)
                ready.set()
                return
            ready.set()
            loop.run_forever()

        t = threading.Thread(target=runner, name="rt-core-loop", daemon=True)
        t.start()
        driver.loop_thread = t
        if not ready.wait(timeout=30):
            raise RuntimeError("head service failed to start")
        if boot_err:
            raise boot_err[0]
        driver._install_ref_hooks()
        driver._start_pusher_shards()
        _cluster = LocalCluster(
            head, driver.gcs_addr, job_id, driver,
            session_dir=session_dir,
        )
        n_cpus = num_cpus if num_cpus is not None else (os.cpu_count() or 1)
        node_res = dict(resources or {})
        node_res["CPU"] = float(n_cpus)
        # Accelerator autodetection (reference: _private/accelerators/):
        # explicit resources always win; detection fills the gaps — and
        # only on ONE simulated node, since all num_nodes processes share
        # this machine's physical chips.
        from ray_tpu._private.accelerators import (
            detect_node_accelerators,
            detect_node_labels,
        )

        accel_res = {
            k: v for k, v in detect_node_accelerators().items()
            if k not in node_res
        }
        accel_labels = detect_node_labels()
        for i in range(num_nodes):
            res_i = dict(node_res)
            labels_i = dict(labels or {})
            if i == 0:
                res_i.update(accel_res)
                labels_i = {**accel_labels, **labels_i}
            _cluster.add_node(
                res_i, labels=labels_i, env=_node_env, wait=False
            )
        # 120s: a node spawn is ~2-4s cold, but a loaded single-core host
        # (CI running a whole suite) can stretch it past the old 30s —
        # and a timeout here used to strand half-initialized state.
        _cluster.wait_for_nodes(num_nodes, timeout=120.0)
        from ray_tpu._private.config import rt_config as _rtc

        if int(_rtc.warm_workers) > 0:
            # Warm worker pool: prefork standby node processes in the
            # background (non-blocking) — add_node consumes one instead
            # of a cold spawn, and the head auto-activates one when
            # demand outgrows schedulable capacity.
            _cluster.start_warm_pool(
                int(_rtc.warm_workers), env=_node_env
            )
    else:
        # Explicit address on the head's own machine: the local
        # address file supplies the token (the `connect with:` hint
        # raytpu start prints must work in a fresh shell). Remote
        # drivers set RT_AUTH_TOKEN themselves.
        if "RT_AUTH_TOKEN" not in os.environ:
            from ray_tpu._private import auth as _auth
            from ray_tpu._private.head_main import read_address_file

            finfo = read_address_file()
            if finfo and finfo.get("address") == address:
                if _auth.adopt_token(finfo):
                    _token_set_by_init = True
        host, port = address.rsplit(":", 1)
        driver = CoreWorker(
            is_driver=True, gcs_addr=(host, int(port)), job_id=job_id
        )
        # assigned before start: a mid-connect failure must be cleanable
        _worker_mod.global_worker = driver
        driver.start_driver()
    if _system_config:
        # Publish to the head KV so later-connecting workers (remote
        # clusters, rejoin after head restart) apply the overrides at
        # registration (_connect_gcs reads __rt/system_config).
        import json as _json

        w = _worker_mod.global_worker
        w.run_sync(w.gcs.call(
            "kv_put", {"ns": "__rt", "key": "system_config"},
            [_json.dumps(_system_config).encode()],
        ))
    atexit.register(shutdown)
    from ray_tpu._private.usage_stats import record_session_start

    record_session_start(extra={"mode": "connect" if address else "local"})
    return ClientContext(driver)


class ClientContext:
    def __init__(self, worker: CoreWorker):
        self.worker = worker
        self.address_info = {
            "gcs_address": worker.gcs_addr,
            "node_id": worker.node_id,
        }

    def __enter__(self):
        return self

    def __exit__(self, *a):
        shutdown()


def shutdown():
    atexit.unregister(shutdown)
    if _worker_mod.global_worker is None and _cluster is None:
        return
    _teardown_globals()


def remote(*args, **kwargs):
    """``@remote`` decorator for functions and classes (reference:
    ``python/ray/_private/worker.py:3479``)."""

    def make(target):
        if inspect.isclass(target):
            return ActorClass(target, kwargs)
        return RemoteFunction(target, kwargs)

    if len(args) == 1 and not kwargs and (
        inspect.isfunction(args[0]) or inspect.isclass(args[0])
    ):
        return make(args[0])
    if args:
        raise TypeError("@remote options must be keyword arguments")
    return make


def get(refs, *, timeout: Optional[float] = None):
    w = get_global_worker()
    if isinstance(refs, ObjectRef):
        return w.get(refs, timeout)
    if isinstance(refs, (list, tuple)):
        if not all(isinstance(r, ObjectRef) for r in refs):
            raise TypeError("get() accepts an ObjectRef or a list of ObjectRefs")
        return w.get(list(refs), timeout)
    raise TypeError(f"get() got {type(refs)}")


def put(value: Any) -> ObjectRef:
    return get_global_worker().put(value)


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    return get_global_worker().wait(
        list(refs), num_returns=num_returns, timeout=timeout, fetch_local=fetch_local
    )


def kill(actor: ActorHandle, *, no_restart: bool = True):
    get_global_worker().kill_actor(actor._actor_id_hex, no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    """Best-effort cancellation of a queued task (running tasks on TPU hosts
    are compiled steps and are not preempted in round 1)."""
    # Round-1: cancellation of queued-but-unleased work only.
    return None


def get_actor(name: str, namespace: str = "default") -> ActorHandle:
    w = get_global_worker()
    h = w.run_sync(w.gcs.call("get_actor", {"name": name, "namespace": namespace}))[0]
    if not h.get("found") or h["actor"]["state"] == "DEAD":
        raise ValueError(f"named actor '{name}' not found in namespace '{namespace}'")
    info = h["actor"]
    return ActorHandle(
        info["actor_id"], tuple(info["addr"]) if info["addr"] else None,
        0, info.get("class_name", "Actor"),
        info.get("method_meta") or {},
    )


def nodes() -> List[dict]:
    w = get_global_worker()
    return w.run_sync(w.gcs.call("get_nodes", {}))[0]["nodes"]


def cluster_resources() -> Dict[str, float]:
    total: Dict[str, float] = {}
    for n in nodes():
        if not n["alive"]:
            continue
        for k, v in n["resources"].items():
            total[k] = total.get(k, 0) + v
    return total


def available_resources() -> Dict[str, float]:
    total: Dict[str, float] = {}
    for n in nodes():
        if not n["alive"]:
            continue
        for k, v in n["available"].items():
            total[k] = total.get(k, 0) + v
    return total


def _internal_cluster() -> Optional[LocalCluster]:
    return _cluster
