"""DreamerV3: model-based RL — learn a world model, act in imagination.

Reference analog: ``rllib/algorithms/dreamerv3/`` (the reference's
DreamerV3 port of Hafner et al. 2023). Architecture follows the paper's
core: an RSSM world model (GRU deterministic state + categorical stochastic
latent with straight-through gradients and 1% uniform mixing), symlog
observation/reward regression, a continue head, KL balancing with free
bits, and an actor-critic trained entirely on imagined rollouts with
λ-returns and percentile-EMA return normalization.

Honest simplifications vs the full paper (documented, not hidden):
- reward/value regress symlog targets with MSE instead of two-hot
  distributional heads;
- replayed RSSM states are not carried across training windows (h resets
  at window starts and episode boundaries);
- discrete action spaces only (the reference's DreamerV3 also targets
  discrete control first).

Everything heavy is jitted: one program for the world-model + imagination
update over [B, L] sequence windows; acting rolls the same RSSM one step
per env step inside the runner (recurrent policy — a custom runner class
rides the shared EnvRunnerGroup plumbing).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.rllib import module as rl_module
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig


def symlog(x):
    import jax.numpy as jnp

    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    import jax.numpy as jnp

    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


# ------------------------------------------------------------- world model


def _init_dreamer_params(key, cfg: "DreamerV3Config", obs_dim: int,
                         action_dim: int):
    import jax

    U, D = cfg.units, cfg.deter_dim
    Z = cfg.stoch_dims * cfg.stoch_classes
    ks = iter(jax.random.split(key, 12))
    mlp = rl_module._init_mlp
    dtype = np.float32
    return {
        "wm": {
            # posterior q(z | h, obs)
            "post": mlp(next(ks), [D + obs_dim, U, Z], dtype),
            # prior p(z | h)
            "prior": mlp(next(ks), [D, U, Z], dtype),
            # GRU: gate block (reset/update) + candidate block — split
            # weights so each step evaluates each matmul once
            "gru": {
                "gates": mlp(next(ks), [Z + action_dim + D, 2 * D], dtype),
                "cand": mlp(next(ks), [Z + action_dim + D, D], dtype),
            },
            "decoder": mlp(next(ks), [D + Z, U, obs_dim], dtype),
            "reward": mlp(next(ks), [D + Z, U, 1], dtype),
            "cont": mlp(next(ks), [D + Z, U, 1], dtype),
        },
        "actor": mlp(next(ks), [D + Z, U, action_dim], dtype),
        "critic": mlp(next(ks), [D + Z, U, 1], dtype),
    }


def _gru_step(gru, h, x):
    """GRU cell: gate block on [x, h], candidate block on [x, r*h]."""
    import jax
    import jax.numpy as jnp

    D = h.shape[-1]
    gates = rl_module._mlp(gru["gates"], jnp.concatenate([x, h], -1))
    r = jax.nn.sigmoid(gates[..., :D])
    u = jax.nn.sigmoid(gates[..., D:])
    cand = jnp.tanh(rl_module._mlp(
        gru["cand"], jnp.concatenate([x, r * h], -1)
    ))
    return u * h + (1 - u) * cand


def _latent_dist(logits, cfg):
    """Per-dim categorical probs with 1% uniform mixing (paper trick:
    keeps KL finite and exploration alive)."""
    import jax
    import jax.numpy as jnp

    lg = logits.reshape(*logits.shape[:-1], cfg.stoch_dims,
                        cfg.stoch_classes)
    probs = jax.nn.softmax(lg, -1)
    return 0.99 * probs + 0.01 / cfg.stoch_classes


def _sample_latent(probs, key):
    """Straight-through one-hot sample, flattened to [.., Z]."""
    import jax
    import jax.numpy as jnp

    idx = jax.random.categorical(key, jnp.log(probs), -1)
    onehot = jax.nn.one_hot(idx, probs.shape[-1], dtype=probs.dtype)
    st = onehot + probs - jax.lax.stop_gradient(probs)
    return st.reshape(*st.shape[:-2], -1)


class SequenceReplay:
    """Per-env-column step ring; samples contiguous [L] windows.

    Episode boundaries ride an ``is_first`` flag derived from the stored
    dones so the RSSM can reset inside a window."""

    def __init__(self, capacity_per_env: int, num_envs: int, obs_dim: int,
                 seed: int = 0):
        C, N = capacity_per_env, num_envs
        self.obs = np.zeros((N, C, obs_dim), np.float32)
        self.actions = np.zeros((N, C), np.int32)
        self.rewards = np.zeros((N, C), np.float32)
        self.cont = np.ones((N, C), np.float32)
        self.is_first = np.zeros((N, C), np.float32)
        self.cap = C
        self.pos = 0
        self.size = 0
        self._last_done = np.zeros((N,), np.float32)
        self._rng = np.random.RandomState(seed)

    def add_fragments(self, batch: Dict[str, np.ndarray]):
        obs = batch["obs"]            # [T, N, d]
        T, N = obs.shape[:2]
        n_buf = self.obs.shape[0]
        if N != n_buf:
            # Runner loss/respawn changed the column count: remap the
            # incoming streams onto the buffer's columns (cycling when
            # short) and mark every column as restarting — column
            # identity broke, so no stream may look continuous across
            # the outage.
            sel = np.arange(n_buf) % N
            batch = {k: v[:, sel] for k, v in batch.items()}
            obs = batch["obs"]
            self._last_done[:] = 1.0
        for t in range(T):
            p = (self.pos + t) % self.cap
            self.obs[:, p] = obs[t]
            self.actions[:, p] = batch["actions"][t]
            self.rewards[:, p] = batch["rewards"][t]
            self.cont[:, p] = 1.0 - batch["dones"][t]
            if t == 0:
                self.is_first[:, p] = self._last_done
            else:
                self.is_first[:, p] = batch["dones"][t - 1]
        self._last_done = batch["dones"][-1]
        self.pos = (self.pos + T) % self.cap
        self.size = min(self.size + T, self.cap)

    def sample(self, batch: int, length: int) -> Dict[str, np.ndarray]:
        N = self.obs.shape[0]
        # valid starts avoid the ring seam (pos..pos+L crosses old/new)
        out = {k: [] for k in
               ("obs", "actions", "rewards", "cont", "is_first")}
        for _ in range(batch):
            env = self._rng.randint(N)
            if self.size < self.cap:
                start = self._rng.randint(0, max(self.size - length, 1))
            else:
                off = self._rng.randint(0, self.cap - length)
                start = (self.pos + off) % self.cap
            idx = (start + np.arange(length)) % self.cap
            out["obs"].append(self.obs[env, idx])
            out["actions"].append(self.actions[env, idx])
            out["rewards"].append(self.rewards[env, idx])
            out["cont"].append(self.cont[env, idx])
            first = self.is_first[env, idx].copy()
            first[0] = 1.0  # window start: no carried state (documented)
            out["is_first"].append(first)
        return {k: np.stack(v) for k, v in out.items()}


class DreamerEnvRunner:
    """Recurrent-policy env runner: rolls the RSSM one step per env step
    (posterior latent from the live observation, actor on [h, z]).
    Constructor signature matches SingleAgentEnvRunner so it rides the
    shared EnvRunnerGroup."""

    def __init__(self, env_creator, num_envs: int, fragment_len: int,
                 module_config: dict, seed: int = 0, gamma: float = 0.99,
                 env_to_module=None, module_to_env=None):
        import jax

        self.envs = [env_creator() for _ in range(num_envs)]
        self.num_envs = num_envs
        self.fragment_len = fragment_len
        mc = dict(module_config)
        self.cfg = DreamerV3Config._hp_view(mc)
        self.obs_dim = int(mc["obs_dim"])
        self.action_dim = int(mc["action_dim"])
        self.params = None
        self.rng = jax.random.PRNGKey(seed)
        cfg = self.cfg

        def act(params, h, obs, key):
            import jax.numpy as jnp

            k1, k2 = jax.random.split(key)
            post_in = jnp.concatenate([h, symlog(obs)], -1)
            probs = _latent_dist(
                rl_module._mlp(params["wm"]["post"], post_in), cfg
            )
            z = _sample_latent(probs, k1)
            feat = jnp.concatenate([h, z], -1)
            logits = rl_module._mlp(params["actor"], feat)
            a = jax.random.categorical(k2, logits)
            onehot = jax.nn.one_hot(a, logits.shape[-1])
            h2 = _gru_step(
                params["wm"]["gru"], h, jnp.concatenate([z, onehot], -1)
            )
            return a, h2

        self._act = jax.jit(act)
        self.h = np.zeros((num_envs, cfg.deter_dim), np.float32)
        self.obs = np.stack([
            np.asarray(e.reset(seed=seed * 10_000 + i)[0],
                       np.float32).ravel()
            for i, e in enumerate(self.envs)
        ])
        self._ep_return = np.zeros(num_envs)
        self._ep_len = np.zeros(num_envs, np.int64)
        self._completed = []
        self._total_steps = 0

    def set_weights(self, params):
        self.params = params

    def get_connector_state(self):
        return {}

    def set_connector_state(self, state):
        pass

    def sample(self) -> Dict[str, np.ndarray]:
        import jax

        assert self.params is not None
        T, N = self.fragment_len, self.num_envs
        obs_buf = np.empty((T, N, self.obs.shape[1]), np.float32)
        act_buf = np.empty((T, N), np.int32)
        rew_buf = np.empty((T, N), np.float32)
        done_buf = np.empty((T, N), np.float32)
        for t in range(T):
            self.rng, k = jax.random.split(self.rng)
            a, h2 = self._act(self.params, self.h, self.obs, k)
            a = np.asarray(a)
            # np.array (copy): asarray of a jax array is READ-ONLY and the
            # episode-reset write below would throw
            self.h = np.array(h2)
            obs_buf[t] = self.obs
            act_buf[t] = a
            for i, env in enumerate(self.envs):
                nobs, rew, term, trunc, _ = env.step(int(a[i]))
                done = term or trunc
                rew_buf[t, i] = rew
                done_buf[t, i] = float(done)
                self._ep_return[i] += float(rew)
                self._ep_len[i] += 1
                if done:
                    self._completed.append(
                        (self._ep_return[i], int(self._ep_len[i]))
                    )
                    self._ep_return[i] = 0.0
                    self._ep_len[i] = 0
                    nobs = env.reset()[0]
                    self.h[i] = 0.0  # recurrent state dies with the episode
                self.obs[i] = np.asarray(nobs, np.float32).ravel()
        self._total_steps += T * N
        return {
            "obs": obs_buf, "actions": act_buf, "rewards": rew_buf,
            "dones": done_buf,
        }

    def metrics(self) -> Dict[str, Any]:
        completed, self._completed = self._completed, []
        return {
            "num_episodes": len(completed),
            "episode_returns": [r for r, _ in completed],
            "episode_lengths": [l for _, l in completed],
            "total_steps": self._total_steps,
        }

    def ping(self):
        return True


# ---------------------------------------------------------------- algorithm


class DreamerV3Config(AlgorithmConfig):
    algo_name = "dreamerv3"

    def __init__(self):
        super().__init__()
        self.training(lr=1e-3, gamma=0.997)  # wm lr: 3e-4 plateaus long
        self.units = 128
        self.deter_dim = 128
        self.stoch_dims = 8
        self.stoch_classes = 8
        self.seq_len = 16
        self.batch_seq = 16
        self.imagine_horizon = 10
        self.replay_capacity = 20_000     # steps per env column
        self.min_replay_size = 500
        self.updates_per_step = 4
        self.actor_lr = 3e-4
        self.critic_lr = 3e-4
        self.kl_dyn = 0.5
        self.kl_rep = 0.1
        self.reward_loss_scale = 5.0  # MSE reward needs weight vs recon
        self.critic_ema_tau = 0.02    # slow critic for return bootstrap
        self.free_bits = 1.0
        self.entropy_coeff = 1e-2  # strong enough that early
        # world-model noise cannot collapse the policy before the model
        # becomes accurate (advantages are range-normalized, so the
        # optimal action still dominates at convergence)
        self.lam = 0.95

    _HP_KEYS = ("units", "deter_dim", "stoch_dims", "stoch_classes")

    def runner_module_config(self, base: rl_module.RLModuleConfig) -> dict:
        mc = dict(base.__dict__)
        for k in self._HP_KEYS:
            mc[f"dreamer_{k}"] = getattr(self, k)
        return mc

    @staticmethod
    def _hp_view(mc: dict) -> "DreamerV3Config":
        cfg = DreamerV3Config()
        for k in DreamerV3Config._HP_KEYS:
            if f"dreamer_{k}" in mc:
                setattr(cfg, k, mc.pop(f"dreamer_{k}"))
        return cfg

    def build_algo(self) -> "DreamerV3":
        return DreamerV3(self)


class DreamerV3(Algorithm):
    def __init__(self, config: DreamerV3Config):
        import jax
        import jax.numpy as jnp
        import optax

        self._init_common(config)
        if not self.module_config.discrete:
            raise ValueError(
                "DreamerV3 here supports discrete action spaces"
            )
        cfg = config
        obs_dim = self.module_config.obs_dim
        A = self.module_config.action_dim
        Z = cfg.stoch_dims * cfg.stoch_classes

        key = jax.random.PRNGKey(config.seed)
        self.params = _init_dreamer_params(key, cfg, obs_dim, A)
        # EMA "slow" critic (paper): λ-return bootstraps read it, breaking
        # the self-bootstrap feedback that otherwise inflates returns
        self.params["critic_slow"] = jax.tree.map(
            jnp.copy, self.params["critic"]
        )
        self.wm_opt = optax.adam(cfg.hp.lr)
        self.actor_opt = optax.adam(cfg.actor_lr)
        self.critic_opt = optax.adam(cfg.critic_lr)
        self.opt_state = {
            "wm": self.wm_opt.init(self.params["wm"]),
            "actor": self.actor_opt.init(self.params["actor"]),
            "critic": self.critic_opt.init(self.params["critic"]),
        }
        # percentile-EMA return scale (paper's robust normalizer)
        self.ret_scale = jnp.float32(1.0)
        self._update_key = jax.random.PRNGKey(config.seed + 1)

        gamma, lam = cfg.hp.gamma, cfg.lam
        H = cfg.imagine_horizon

        def kl_cat(p, q):
            # sum over stoch dims of per-dim categorical KLs
            import jax.numpy as jnp

            return jnp.sum(
                jnp.sum(p * (jnp.log(p) - jnp.log(q)), -1), -1
            )

        def observe(wm, batch, key):
            """Posterior roll over [B, L]: returns feats [B, L, D+Z] and
            the KL terms."""
            import jax.numpy as jnp

            B, L = batch["obs"].shape[:2]
            obs_sym = symlog(batch["obs"])
            a_onehot = jax.nn.one_hot(batch["actions"], A)
            keys = jax.random.split(key, L)

            def step(h, t):
                h = h * (1.0 - batch["is_first"][:, t][:, None])
                post_in = jnp.concatenate([h, obs_sym[:, t]], -1)
                post = _latent_dist(rl_module._mlp(wm["post"], post_in),
                                    cfg)
                prior = _latent_dist(rl_module._mlp(wm["prior"], h), cfg)
                z = _sample_latent(post, keys[t])
                feat = jnp.concatenate([h, z], -1)
                h2 = _gru_step(
                    wm["gru"], h,
                    jnp.concatenate([z, a_onehot[:, t]], -1),
                )
                return h2, (feat, post, prior)

            h0 = jnp.zeros((B, cfg.deter_dim))
            _, (feats, posts, priors) = jax.lax.scan(
                step, h0, jnp.arange(L)
            )
            # scan stacks on axis 0 = time; move to [B, L, ...]
            feats = jnp.moveaxis(feats, 0, 1)
            posts = jnp.moveaxis(posts, 0, 1)
            priors = jnp.moveaxis(priors, 0, 1)
            return feats, posts, priors

        def wm_loss(wm, batch, key):
            import jax.numpy as jnp

            feats, posts, priors = observe(wm, batch, key)
            recon = rl_module._mlp(wm["decoder"], feats)
            l_obs = jnp.mean(
                jnp.sum((recon - symlog(batch["obs"])) ** 2, -1)
            )
            # Reward/continue alignment: r_t is the consequence of a_t,
            # visible only in the POST-action state s_{t+1} (h_{t+1}
            # carries a_t through the GRU) — exactly how imagination
            # reads rewards off rolled states. Pairs that straddle an
            # episode boundary (post-reset state vs pre-reset reward)
            # are masked out.
            mask = 1.0 - batch["is_first"][:, 1:]
            denom = jnp.maximum(jnp.sum(mask), 1.0)
            r_hat = rl_module._mlp(wm["reward"], feats)[..., 0]
            l_rew = jnp.sum(
                mask * (r_hat[:, 1:] - symlog(batch["rewards"][:, :-1]))
                ** 2
            ) / denom
            c_logit = rl_module._mlp(wm["cont"], feats)[..., 0]
            cl = c_logit[:, 1:]
            ct = batch["cont"][:, :-1]
            l_cont = jnp.sum(mask * (
                jnp.maximum(cl, 0) - cl * ct
                + jnp.log1p(jnp.exp(-jnp.abs(cl)))
            )) / denom
            sg = jax.lax.stop_gradient
            dyn = jnp.maximum(
                jnp.mean(kl_cat(sg(posts), priors)), cfg.free_bits
            )
            rep = jnp.maximum(
                jnp.mean(kl_cat(posts, sg(priors))), cfg.free_bits
            )
            loss = l_obs + cfg.reward_loss_scale * l_rew + l_cont \
                + cfg.kl_dyn * dyn + cfg.kl_rep * rep
            return loss, (feats, l_obs, l_rew, dyn)

        def imagine(params, feats0, key):
            """Roll H steps from flattened starts through the PRIOR with
            actor actions. Returns feats [H+1, M, D+Z], logps, entropies."""
            import jax.numpy as jnp

            wm = params["wm"]
            M = feats0.shape[0]
            h = feats0[:, :cfg.deter_dim]
            z = feats0[:, cfg.deter_dim:]
            keys = jax.random.split(key, H)

            def step(carry, k):
                h, z = carry
                feat = jnp.concatenate([h, z], -1)
                logits = rl_module._mlp(params["actor"], feat)
                logp_all = jax.nn.log_softmax(logits)
                k1, k2 = jax.random.split(k)
                a = jax.random.categorical(k1, logits)
                logp = jnp.take_along_axis(
                    logp_all, a[:, None], -1
                )[:, 0]
                ent = -jnp.sum(jnp.exp(logp_all) * logp_all, -1)
                onehot = jax.nn.one_hot(a, A)
                h2 = _gru_step(
                    wm["gru"], h, jnp.concatenate([z, onehot], -1)
                )
                prior = _latent_dist(rl_module._mlp(wm["prior"], h2), cfg)
                z2 = _sample_latent(prior, k2)
                return (h2, z2), (jnp.concatenate([h2, z2], -1), logp, ent)

            (_, _), (feats, logps, ents) = jax.lax.scan(
                step, (h, z), keys
            )
            feats = jnp.concatenate(
                [jnp.concatenate([h, z], -1)[None], feats], 0
            )
            return feats, logps, ents

        def ac_loss(actor, critic, wm_feats, params, key, ret_scale):
            import jax.numpy as jnp

            sg = jax.lax.stop_gradient
            p = {"wm": sg(params["wm"]), "actor": actor}
            starts = sg(wm_feats.reshape(-1, wm_feats.shape[-1]))
            feats, logps, ents = imagine(p, starts, key)
            wm = sg(params["wm"])
            rew = symexp(rl_module._mlp(wm["reward"], feats)[..., 0])
            cont = jax.nn.sigmoid(
                rl_module._mlp(wm["cont"], feats)[..., 0]
            )
            # bootstrap values come from the EMA critic (sg'd): the live
            # critic chasing its own bootstrap diverges
            vals = symexp(rl_module._mlp(
                sg(params["critic_slow"]), feats
            )[..., 0])
            disc = gamma * cont
            # λ-returns, backward over the horizon
            def lam_step(nxt, t):
                r = rew[t + 1] + disc[t + 1] * (
                    (1 - lam) * vals[t + 1] + lam * nxt
                )
                return r, r

            last = vals[-1]
            _, rets = jax.lax.scan(
                lam_step, last, jnp.arange(H - 1, -1, -1)
            )
            rets = rets[::-1]                      # [H, M]
            adv = sg((rets - vals[:-1]) / ret_scale)
            weight = jnp.cumprod(
                jnp.concatenate([jnp.ones((1,) + disc.shape[1:]),
                                 disc[:-1]], 0), 0
            )[:H]
            weight = sg(weight)
            a_loss = -jnp.mean(weight * (logps * adv
                                         + cfg.entropy_coeff * ents))
            v_pred = rl_module._mlp(critic, sg(feats[:-1]))[..., 0]
            c_loss = jnp.mean(weight * (v_pred - sg(symlog(rets))) ** 2)
            # robust scale: EMA of the 5-95 percentile return range
            lo, hi = jnp.percentile(rets, 5), jnp.percentile(rets, 95)
            new_scale = 0.99 * ret_scale + 0.01 * jnp.maximum(hi - lo, 1.0)
            return a_loss + c_loss, (a_loss, c_loss, new_scale,
                                     jnp.mean(rets))

        def update(params, opt_state, ret_scale, batch, key):
            import jax.numpy as jnp

            k_wm, k_im = jax.random.split(key)
            (wl, (feats, l_obs, l_rew, dyn)), wm_grads = (
                jax.value_and_grad(wm_loss, has_aux=True)(
                    params["wm"], batch, k_wm
                )
            )
            upd, opt_wm = self.wm_opt.update(
                wm_grads, opt_state["wm"], params["wm"]
            )
            import optax as _optax

            wm_new = _optax.apply_updates(params["wm"], upd)
            params = {**params, "wm": wm_new}

            def both(ac):
                return ac_loss(ac["actor"], ac["critic"], feats, params,
                               k_im, ret_scale)

            (tl, (a_l, c_l, new_scale, ret_mean)), grads = (
                jax.value_and_grad(both, has_aux=True)(
                    {"actor": params["actor"], "critic": params["critic"]}
                )
            )
            upd_a, opt_a = self.actor_opt.update(
                grads["actor"], opt_state["actor"], params["actor"]
            )
            upd_c, opt_c = self.critic_opt.update(
                grads["critic"], opt_state["critic"], params["critic"]
            )
            critic_new = _optax.apply_updates(params["critic"], upd_c)
            params = {
                **params,
                "actor": _optax.apply_updates(params["actor"], upd_a),
                "critic": critic_new,
                "critic_slow": jax.tree.map(
                    lambda s_, c: (1 - cfg.critic_ema_tau) * s_
                    + cfg.critic_ema_tau * c,
                    params["critic_slow"], critic_new,
                ),
            }
            metrics = {
                "wm_loss": wl, "obs_loss": l_obs, "reward_loss": l_rew,
                "kl_dyn": dyn, "actor_loss": a_l, "critic_loss": c_l,
                "imagined_return": ret_mean,
            }
            return params, {
                "wm": opt_wm, "actor": opt_a, "critic": opt_c
            }, new_scale, metrics

        self._update = jax.jit(update)

        from ray_tpu.rllib.env_runner import EnvRunnerGroup

        self.runner_group = EnvRunnerGroup(
            config.get_env_creator(), config.num_env_runners,
            config.num_envs_per_runner, config.rollout_fragment_length,
            config.runner_module_config(self.module_config),
            seed=config.seed, gamma=cfg.hp.gamma,
            runner_cls=DreamerEnvRunner,
        )
        self.buffer = SequenceReplay(
            cfg.replay_capacity,
            config.num_env_runners * config.num_envs_per_runner,
            obs_dim, seed=config.seed,
        )
        self.runner_group.sync_weights(jax.device_get(self.params))

    # ---------------------------------------------------------------- train

    def training_step(self) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp

        fragments = self.runner_group.sample()
        if not fragments:
            self._last_step_count = 0
            return {"num_healthy_runners": 0}
        batch = self._build_batch(fragments)
        self.buffer.add_fragments(batch)
        self._record_env_steps(batch)

        metrics: Dict[str, float] = {"replay_size": float(self.buffer.size)}
        if self.buffer.size >= self.config.min_replay_size:
            last = {}
            for _ in range(self.config.updates_per_step):
                self._update_key, k = jax.random.split(self._update_key)
                mb = {
                    k2: jnp.asarray(v) for k2, v in self.buffer.sample(
                        self.config.batch_seq, self.config.seq_len
                    ).items()
                }
                self.params, self.opt_state, self.ret_scale, last = (
                    self._update(self.params, self.opt_state,
                                 self.ret_scale, mb, k)
                )
            metrics.update({k: float(v) for k, v in last.items()})
            metrics["total_loss"] = metrics.get("wm_loss", 0.0)
        self.runner_group.sync_weights(jax.device_get(self.params))
        return metrics

    # ------------------------------------------------------------ lifecycle

    def get_weights(self):
        import jax

        return jax.device_get(self.params)

    def save(self, path: str) -> str:
        import os
        import pickle

        import jax

        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "algorithm_state.pkl"), "wb") as f:
            pickle.dump({
                "params": jax.device_get(self.params),
                "ret_scale": float(self.ret_scale),
                "iteration": self.iteration,
                "total_env_steps": self._total_env_steps,
                "algo": "dreamerv3",
            }, f)
        return path

    def restore(self, path: str):
        import os
        import pickle

        import jax
        import jax.numpy as jnp

        with open(os.path.join(path, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self.params = jax.tree.map(jnp.asarray, state["params"])
        self.ret_scale = jnp.float32(state["ret_scale"])
        self.iteration = state["iteration"]
        self._total_env_steps = state.get("total_env_steps", 0)
        self.runner_group.sync_weights(jax.device_get(self.params))
