"""DQN: Q-learning with replay, target network, epsilon-greedy exploration.

Reference analog: ``rllib/algorithms/dqn/`` (new API stack DQN). The Q
network reuses the RLModule MLP (``pi`` head = Q-values,
exploration="epsilon_greedy"); the TD update is one jitted program over
replay minibatches; the target network is a second param pytree synced every
``target_update_freq`` gradient steps; epsilon decays per training step and
reaches runners through the normal weight broadcast (it lives in params).
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ray_tpu.rllib import module as rl_module
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig


class ReplayBuffer:
    """Flat numpy ring of transitions (reference:
    ``rllib/utils/replay_buffers``)."""

    def __init__(self, capacity: int, obs_dim: int):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.next_obs = np.zeros((capacity, obs_dim), np.float32)
        self.actions = np.zeros((capacity,), np.int32)
        self.rewards = np.zeros((capacity,), np.float32)
        self.dones = np.zeros((capacity,), np.float32)
        self.size = 0
        self._pos = 0
        self._rng = np.random.RandomState(0)

    def add_fragments(self, batch: Dict[str, np.ndarray]):
        """Consume a [T, N] fragment batch: transitions t -> t+1 (the last
        step of each column has no in-fragment successor and is dropped).
        Ring insertion is vectorized — this runs every training step."""
        obs, act = batch["obs"], batch["actions"]
        rew, done = batch["rewards"], batch["dones"]
        T = obs.shape[0]
        if T < 2:
            return
        o = obs[:-1].reshape(-1, obs.shape[-1])
        no = obs[1:].reshape(-1, obs.shape[-1])
        a = act[:-1].reshape(-1)
        r = rew[:-1].reshape(-1)
        d = done[:-1].reshape(-1)
        n = o.shape[0]
        if n >= self.capacity:  # keep only the newest capacity-many
            o, no, a, r, d = (x[-self.capacity:] for x in (o, no, a, r, d))
            n = self.capacity
        idx = (self._pos + np.arange(n)) % self.capacity
        self.obs[idx] = o
        self.next_obs[idx] = no
        self.actions[idx] = a
        self.rewards[idx] = r
        self.dones[idx] = d
        self._pos = (self._pos + n) % self.capacity
        self.size = min(self.size + n, self.capacity)

    def sample(self, n: int) -> Dict[str, np.ndarray]:
        idx = self._rng.randint(0, self.size, n)
        return {
            "obs": self.obs[idx],
            "next_obs": self.next_obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "dones": self.dones[idx],
        }


class DQNConfig(AlgorithmConfig):
    algo_name = "dqn"

    def __init__(self):
        super().__init__()
        self.training(lr=1e-3, gamma=0.99)
        self.replay_capacity = 50_000
        self.learn_batch_size = 64
        self.updates_per_step = 16
        self.target_update_freq = 100     # gradient steps between syncs
        self.min_replay_size = 500
        self.epsilon_start = 1.0
        self.epsilon_end = 0.05
        self.epsilon_decay_steps = 50     # training_step calls to anneal over
        self.double_q = True

    def build_algo(self) -> "DQN":
        return DQN(self)


class DQN(Algorithm):
    def __init__(self, config: DQNConfig):
        import jax
        import jax.numpy as jnp
        import optax

        import dataclasses

        # Q-head module: pi outputs Q-values; epsilon-greedy exploration
        self._init_common(config)
        if not self.module_config.discrete:
            raise ValueError(
                "DQN requires a discrete action space; "
                f"{config.env or config.env_creator} has a continuous one"
            )
        self.module_config = dataclasses.replace(
            self.module_config, exploration="epsilon_greedy"
        )
        cfg = self.module_config
        hp = config.hp
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(hp.grad_clip), optax.adam(hp.lr)
        )
        key = jax.random.PRNGKey(config.seed)
        self.q_params = rl_module.init_params(cfg, key)
        self.q_params["epsilon"] = jnp.float32(config.epsilon_start)
        self.target_params = jax.tree.map(jnp.copy, self.q_params)
        self.opt_state = self.optimizer.init(self.q_params)
        self.buffer = ReplayBuffer(config.replay_capacity, cfg.obs_dim)
        self._grad_steps = 0

        gamma, double_q = hp.gamma, config.double_q

        def td_update(params, target, opt_state, batch):
            def loss_fn(p):
                q = rl_module.forward_policy(p, cfg, batch["obs"])
                q_sa = jnp.take_along_axis(
                    q, batch["actions"][:, None].astype(jnp.int32), -1
                )[:, 0]
                q_next_t = rl_module.forward_policy(
                    target, cfg, batch["next_obs"]
                )
                if double_q:
                    # Double DQN: online net picks, target net evaluates
                    q_next_on = rl_module.forward_policy(
                        p, cfg, batch["next_obs"]
                    )
                    a_star = jnp.argmax(q_next_on, axis=-1)
                    q_next = jnp.take_along_axis(
                        q_next_t, a_star[:, None], -1
                    )[:, 0]
                else:
                    q_next = jnp.max(q_next_t, axis=-1)
                tgt = batch["rewards"] + gamma * (1 - batch["dones"]) * (
                    jax.lax.stop_gradient(q_next)
                )
                td = q_sa - tgt
                # huber
                loss = jnp.mean(
                    jnp.where(jnp.abs(td) < 1.0, 0.5 * td ** 2,
                              jnp.abs(td) - 0.5)
                )
                return loss, jnp.mean(jnp.abs(td))

            (loss, td_abs), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params
            )
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, td_abs

        self._td_update = jax.jit(td_update)

        from ray_tpu.rllib.env_runner import EnvRunnerGroup

        self.runner_group = EnvRunnerGroup(
            config.get_env_creator(), config.num_env_runners,
            config.num_envs_per_runner, config.rollout_fragment_length,
            self.module_config, seed=config.seed, gamma=hp.gamma,
            env_to_module=config.env_to_module_connector,
            module_to_env=config.module_to_env_connector,
        )
        self.runner_group.sync_weights(jax.device_get(self.q_params))

    # ---------------------------------------------------------------- train

    def training_step(self) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp

        fragments = self.runner_group.sample()
        if not fragments:
            self._last_step_count = 0
            return {"num_healthy_runners": 0}
        batch = self._build_batch(fragments)
        self.buffer.add_fragments(batch)
        self._record_env_steps(batch)

        metrics: Dict[str, float] = {
            "replay_size": float(self.buffer.size),
            "epsilon": float(self.q_params["epsilon"]),
        }
        if self.buffer.size >= self.config.min_replay_size:
            losses = []
            for _ in range(self.config.updates_per_step):
                mb = {
                    k: jnp.asarray(v)
                    for k, v in self.buffer.sample(
                        self.config.learn_batch_size
                    ).items()
                }
                (self.q_params, self.opt_state, loss, td
                 ) = self._td_update(
                    self.q_params, self.target_params, self.opt_state, mb
                )
                losses.append(float(loss))
                self._grad_steps += 1
                if self._grad_steps % self.config.target_update_freq == 0:
                    self.target_params = jax.tree.map(
                        jnp.copy, self.q_params
                    )
            metrics["total_loss"] = float(np.mean(losses))

        # anneal epsilon and broadcast (it rides params)
        frac = min(self.iteration / max(self.config.epsilon_decay_steps, 1),
                   1.0)
        eps = (self.config.epsilon_start
               + (self.config.epsilon_end - self.config.epsilon_start) * frac)
        self.q_params["epsilon"] = jnp.float32(eps)
        self.runner_group.sync_weights(jax.device_get(self.q_params))
        return metrics

    # ------------------------------------------------------------ lifecycle

    def get_weights(self):
        import jax

        return jax.device_get(self.q_params)

    def save(self, path: str) -> str:
        import os
        import pickle

        import jax

        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "algorithm_state.pkl"), "wb") as f:
            pickle.dump({
                "q_params": jax.device_get(self.q_params),
                "target_params": jax.device_get(self.target_params),
                "opt_state": jax.device_get(self.opt_state),
                "iteration": self.iteration,
                "grad_steps": self._grad_steps,
                "total_env_steps": self._total_env_steps,
                "algo": "dqn",
            }, f)
        return path

    def restore(self, path: str):
        import os
        import pickle

        import jax.numpy as jnp
        import jax

        with open(os.path.join(path, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self.q_params = jax.tree.map(jnp.asarray, state["q_params"])
        self.target_params = jax.tree.map(jnp.asarray,
                                          state["target_params"])
        self.opt_state = jax.tree.map(
            lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x,
            state["opt_state"],
        )
        self.iteration = state["iteration"]
        self._grad_steps = state["grad_steps"]
        self._total_env_steps = state.get("total_env_steps", 0)
        self.runner_group.sync_weights(jax.device_get(self.q_params))
