from ray_tpu.rllib.algorithms.appo import APPO, APPOConfig
from ray_tpu.rllib.algorithms.cql import CQL, CQLConfig, IQL, IQLConfig
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.dreamerv3 import DreamerV3, DreamerV3Config
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.algorithms.marwil import BC, BCConfig, MARWIL, MARWILConfig
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig
from ray_tpu.rllib.algorithms.tqc import TQC, TQCConfig

__all__ = [
    "APPO", "APPOConfig", "CQL", "CQLConfig", "IQL", "IQLConfig",
    "PPO", "PPOConfig", "IMPALA", "IMPALAConfig", "DQN", "DQNConfig",
    "DreamerV3", "DreamerV3Config",
    "SAC", "SACConfig", "TQC", "TQCConfig",
    "MARWIL", "MARWILConfig", "BC", "BCConfig",
]
