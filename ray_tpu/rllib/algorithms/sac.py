"""SAC: soft actor-critic for continuous control.

Reference analog: ``rllib/algorithms/sac/`` (new API stack SAC). Off-policy
maximum-entropy RL: a tanh-squashed gaussian policy (reparameterized), twin
Q critics with clipped double-Q targets, polyak-averaged target critics, and
automatic entropy-temperature tuning toward a target entropy of -action_dim.
The whole update (critic + actor + alpha) is one jitted program over replay
minibatches; runners explore with the same squashed-gaussian head via the
normal weight broadcast.
"""
from __future__ import annotations

from typing import Dict

import numpy as np

from ray_tpu.rllib import module as rl_module
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig


class ContinuousReplayBuffer:
    """Flat numpy ring of (s, a, r, s', done) with float action vectors."""

    def __init__(self, capacity: int, obs_dim: int, action_dim: int,
                 seed: int = 0):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.next_obs = np.zeros((capacity, obs_dim), np.float32)
        self.actions = np.zeros((capacity, action_dim), np.float32)
        self.rewards = np.zeros((capacity,), np.float32)
        self.dones = np.zeros((capacity,), np.float32)
        self.size = 0
        self._pos = 0
        self._rng = np.random.RandomState(seed)

    def add_fragments(self, batch: Dict[str, np.ndarray]):
        """Consume a [T, N] fragment batch (transitions t -> t+1; the last
        step of each column has no in-fragment successor and is dropped).
        Time-limit-truncated steps are dropped entirely: their stored
        next_obs is the reset observation and SAC has no trained V(s) to
        fold a bootstrap into the reward with."""
        obs, act = batch["obs"], batch["actions"]
        rew, done = batch["rewards"], batch["dones"]
        T = obs.shape[0]
        if T < 2:
            return
        o = obs[:-1].reshape(-1, obs.shape[-1])
        no = obs[1:].reshape(-1, obs.shape[-1])
        a = act[:-1].reshape(-1, act.shape[-1])
        r = rew[:-1].reshape(-1)
        d = done[:-1].reshape(-1)
        trunc = batch.get("truncateds")
        if trunc is not None:
            keep = trunc[:-1].reshape(-1) < 0.5
            o, no, a, r, d = o[keep], no[keep], a[keep], r[keep], d[keep]
        n = o.shape[0]
        if n == 0:
            return
        if n >= self.capacity:
            o, no, a, r, d = (x[-self.capacity:] for x in (o, no, a, r, d))
            n = self.capacity
        idx = (self._pos + np.arange(n)) % self.capacity
        self.obs[idx] = o
        self.next_obs[idx] = no
        self.actions[idx] = a
        self.rewards[idx] = r
        self.dones[idx] = d
        self._pos = (self._pos + n) % self.capacity
        self.size = min(self.size + n, self.capacity)

    def sample(self, n: int) -> Dict[str, np.ndarray]:
        idx = self._rng.randint(0, self.size, n)
        return {
            "obs": self.obs[idx],
            "next_obs": self.next_obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "dones": self.dones[idx],
        }


class SACConfig(AlgorithmConfig):
    algo_name = "sac"

    def __init__(self):
        super().__init__()
        self.training(lr=3e-4, gamma=0.99)
        self.replay_capacity = 100_000
        self.learn_batch_size = 128
        self.updates_per_step = 16
        self.min_replay_size = 500
        self.tau = 0.005                 # polyak rate for target critics
        self.init_alpha = 0.1
        self.target_entropy = None       # None -> -action_dim
        self.critic_hidden = (128, 128)

    def build_algo(self) -> "SAC":
        return SAC(self)


class SAC(Algorithm):
    def __init__(self, config: SACConfig):
        import dataclasses

        import jax
        import jax.numpy as jnp
        import optax

        self._init_common(config)
        if self.module_config.discrete:
            raise ValueError(
                "SAC requires a continuous (Box) action space; "
                f"{config.env or config.env_creator} has a discrete one"
            )
        self.module_config = dataclasses.replace(
            self.module_config, exploration="squashed_gaussian"
        )
        cfg = self.module_config
        hp = config.hp
        A = cfg.action_dim
        target_entropy = (
            config.target_entropy
            if config.target_entropy is not None else -float(A)
        )

        key = jax.random.PRNGKey(config.seed)
        k_pi, k_q1, k_q2 = jax.random.split(key, 3)
        self.pi_params = rl_module.init_params(cfg, k_pi)
        q_sizes = [cfg.obs_dim + A, *config.critic_hidden, 1]
        self.q_params = {
            "q1": rl_module._init_mlp(k_q1, q_sizes, cfg.dtype),
            "q2": rl_module._init_mlp(k_q2, q_sizes, cfg.dtype),
        }
        self.q_target = jax.tree.map(jnp.copy, self.q_params)
        self.log_alpha = jnp.log(jnp.float32(config.init_alpha))

        self.pi_opt = optax.adam(hp.lr)
        self.q_opt = optax.adam(hp.lr)
        self.alpha_opt = optax.adam(hp.lr)
        self.pi_opt_state = self.pi_opt.init(self.pi_params)
        self.q_opt_state = self.q_opt.init(self.q_params)
        self.alpha_opt_state = self.alpha_opt.init(self.log_alpha)

        self.buffer = ContinuousReplayBuffer(
            config.replay_capacity, cfg.obs_dim, A, seed=config.seed
        )
        self._update_key = jax.random.PRNGKey(config.seed + 1)

        gamma, tau = hp.gamma, config.tau

        def q_value(qp, obs, act):
            x = jnp.concatenate([obs, act], -1)
            return rl_module._mlp(qp, x)[..., 0]

        def update(pi_p, q_p, q_t, log_alpha, pi_os, q_os, a_os, batch, rng):
            k_next, k_pi_new = jax.random.split(rng)
            alpha = jnp.exp(log_alpha)

            # ---- critic: clipped double-Q soft target
            mean_n, logstd_n = rl_module.squashed_gaussian_dist(
                pi_p, cfg, batch["next_obs"]
            )
            a_next, logp_next = rl_module.squashed_sample_logp(
                mean_n, logstd_n, k_next
            )
            q_next = jnp.minimum(
                q_value(q_t["q1"], batch["next_obs"], a_next),
                q_value(q_t["q2"], batch["next_obs"], a_next),
            )
            target = batch["rewards"] + gamma * (1 - batch["dones"]) * (
                q_next - alpha * logp_next
            )
            target = jax.lax.stop_gradient(target)

            def critic_loss(q_p):
                q1 = q_value(q_p["q1"], batch["obs"], batch["actions"])
                q2 = q_value(q_p["q2"], batch["obs"], batch["actions"])
                return jnp.mean((q1 - target) ** 2 + (q2 - target) ** 2)

            c_loss, q_grads = jax.value_and_grad(critic_loss)(q_p)
            q_upd, q_os = self.q_opt.update(q_grads, q_os, q_p)
            import optax as _optax

            q_p = _optax.apply_updates(q_p, q_upd)

            # ---- actor: maximize E[min Q - alpha * logp] (reparameterized)
            def actor_loss(pi_p):
                mean, logstd = rl_module.squashed_gaussian_dist(
                    pi_p, cfg, batch["obs"]
                )
                a_new, logp = rl_module.squashed_sample_logp(
                    mean, logstd, k_pi_new
                )
                q_new = jnp.minimum(
                    q_value(q_p["q1"], batch["obs"], a_new),
                    q_value(q_p["q2"], batch["obs"], a_new),
                )
                return jnp.mean(alpha * logp - q_new), jnp.mean(logp)

            (a_loss, mean_logp), pi_grads = jax.value_and_grad(
                actor_loss, has_aux=True
            )(pi_p)
            pi_upd, pi_os = self.pi_opt.update(pi_grads, pi_os, pi_p)
            pi_p = _optax.apply_updates(pi_p, pi_upd)

            # ---- temperature: drive policy entropy toward target_entropy
            def alpha_loss(log_a):
                return -log_a * jax.lax.stop_gradient(
                    mean_logp + target_entropy
                )

            al_loss, a_grad = jax.value_and_grad(alpha_loss)(log_alpha)
            a_upd, a_os = self.alpha_opt.update(a_grad, a_os, log_alpha)
            log_alpha = _optax.apply_updates(log_alpha, a_upd)

            # ---- polyak target update
            q_t = jax.tree.map(
                lambda t, p: (1 - tau) * t + tau * p, q_t, q_p
            )
            metrics = {
                "critic_loss": c_loss,
                "actor_loss": a_loss,
                "alpha_loss": al_loss,
                "alpha": jnp.exp(log_alpha),
                "entropy": -mean_logp,
            }
            return pi_p, q_p, q_t, log_alpha, pi_os, q_os, a_os, metrics

        self._update = jax.jit(update)

        from ray_tpu.rllib.env_runner import EnvRunnerGroup

        self.runner_group = EnvRunnerGroup(
            config.get_env_creator(), config.num_env_runners,
            config.num_envs_per_runner, config.rollout_fragment_length,
            self.module_config, seed=config.seed, gamma=hp.gamma,
        )
        self.runner_group.sync_weights(jax.device_get(self.pi_params))

    # ---------------------------------------------------------------- train

    def training_step(self) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp

        fragments = self.runner_group.sample()
        if not fragments:
            self._last_step_count = 0
            return {"num_healthy_runners": 0}
        batch = self._build_batch(fragments)
        self.buffer.add_fragments(batch)
        self._record_env_steps(batch)

        metrics: Dict[str, float] = {"replay_size": float(self.buffer.size)}
        if self.buffer.size >= self.config.min_replay_size:
            last = {}
            for _ in range(self.config.updates_per_step):
                self._update_key, k = jax.random.split(self._update_key)
                mb = {
                    k2: jnp.asarray(v)
                    for k2, v in self.buffer.sample(
                        self.config.learn_batch_size
                    ).items()
                }
                (self.pi_params, self.q_params, self.q_target,
                 self.log_alpha, self.pi_opt_state, self.q_opt_state,
                 self.alpha_opt_state, last) = self._update(
                    self.pi_params, self.q_params, self.q_target,
                    self.log_alpha, self.pi_opt_state, self.q_opt_state,
                    self.alpha_opt_state, mb, k,
                )
            metrics.update({k: float(v) for k, v in last.items()})
            metrics["total_loss"] = metrics.get("critic_loss", 0.0)
        self.runner_group.sync_weights(jax.device_get(self.pi_params))
        return metrics

    # ------------------------------------------------------------ lifecycle

    def get_weights(self):
        import jax

        return jax.device_get(self.pi_params)

    def save(self, path: str) -> str:
        import os
        import pickle

        import jax

        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "algorithm_state.pkl"), "wb") as f:
            pickle.dump({
                "pi_params": jax.device_get(self.pi_params),
                "q_params": jax.device_get(self.q_params),
                "q_target": jax.device_get(self.q_target),
                "log_alpha": float(self.log_alpha),
                "iteration": self.iteration,
                "total_env_steps": self._total_env_steps,
                "algo": "sac",
            }, f)
        return path

    def restore(self, path: str):
        import os
        import pickle

        import jax
        import jax.numpy as jnp

        with open(os.path.join(path, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        as_jnp = lambda t: jax.tree.map(jnp.asarray, t)  # noqa: E731
        self.pi_params = as_jnp(state["pi_params"])
        self.q_params = as_jnp(state["q_params"])
        self.q_target = as_jnp(state["q_target"])
        self.log_alpha = jnp.float32(state["log_alpha"])
        self.iteration = state["iteration"]
        self._total_env_steps = state.get("total_env_steps", 0)
        self.runner_group.sync_weights(jax.device_get(self.pi_params))
