"""APPO: asynchronous PPO.

Reference analog: ``rllib/algorithms/appo/appo.py`` — IMPALA's
actor-learner architecture (runners sample under stale weights, V-trace
corrects the off-policyness) with PPO's clipped-surrogate loss for stable
updates. Our env-runner group is the natural fit: runners keep producing
fragments between the periodic weight broadcasts, and the learner's jitted
APPO update (``learner.py make_appo_update``) absorbs the staleness.
"""
from __future__ import annotations

from typing import Dict

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig


class APPOConfig(AlgorithmConfig):
    algo_name = "appo"

    def __init__(self):
        super().__init__()
        self.training(
            lr=5e-4, clip_param=0.2, vf_coeff=0.5, entropy_coeff=0.01,
            vtrace_rho_clip=1.0, vtrace_c_clip=1.0,
        )
        self.broadcast_interval = 2  # learner updates between weight syncs

    def build_algo(self) -> "APPO":
        return APPO(self)


class APPO(Algorithm):
    def __init__(self, config: APPOConfig):
        super().__init__(config)
        self._since_broadcast = 0

    def training_step(self) -> Dict[str, float]:
        fragments = self.runner_group.sample()
        if not fragments:
            self._last_step_count = 0
            return {"num_healthy_runners": 0}
        batch = self._build_batch(fragments)
        metrics = self.learner.update(batch)
        self._record_env_steps(batch)
        self._since_broadcast += 1
        if self._since_broadcast >= getattr(self.config,
                                            "broadcast_interval", 1):
            self.runner_group.sync_weights(self.learner.get_weights())
            self._since_broadcast = 0
        return metrics
