"""IMPALA: importance-weighted actor-learner architecture.

Reference analog: ``rllib/algorithms/impala/impala.py``. Sampling and
learning decouple: runners keep producing fragments under slightly stale
weights; the learner corrects the off-policyness with V-trace
(``learner.py vtrace``). Our synchronous loop broadcasts weights every K
updates instead of every step — the staleness V-trace exists to absorb —
which cuts the dominant cost of the reference's async architecture
(weight-sync RPCs) without a queue process.
"""
from __future__ import annotations

from typing import Dict

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig


class IMPALAConfig(AlgorithmConfig):
    algo_name = "impala"

    def __init__(self):
        super().__init__()
        self.training(
            lr=5e-4, vf_coeff=0.5, entropy_coeff=0.01,
            vtrace_rho_clip=1.0, vtrace_c_clip=1.0,
        )
        self.broadcast_interval = 2  # learner updates between weight syncs


class IMPALA(Algorithm):
    def __init__(self, config: IMPALAConfig):
        super().__init__(config)
        self._since_broadcast = 0

    def training_step(self) -> Dict[str, float]:
        fragments = self.runner_group.sample()
        if not fragments:
            self._last_step_count = 0
            return {"num_healthy_runners": 0}
        batch = self._build_batch(fragments)
        metrics = self.learner.update(batch)
        self._record_env_steps(batch)
        self._since_broadcast += 1
        interval = getattr(self.config, "broadcast_interval", 1)
        if self._since_broadcast >= interval:
            self.runner_group.sync_weights(self.learner.get_weights())
            self._since_broadcast = 0
        return metrics
