"""PPO: clipped-surrogate policy optimization.

Reference analog: ``rllib/algorithms/ppo/ppo.py:365`` (PPOConfig + PPO
Algorithm on the new API stack). The loss lives in the jitted learner update
(``ray_tpu/rllib/learner.py make_ppo_update``): GAE advantages, ratio clip,
value MSE, entropy bonus, minibatched SGD epochs — all one XLA program.
"""
from __future__ import annotations

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig


class PPOConfig(AlgorithmConfig):
    algo_name = "ppo"

    def __init__(self):
        super().__init__()
        self.training(
            lr=3e-4, clip_param=0.2, vf_coeff=0.5, entropy_coeff=0.01,
            num_sgd_epochs=4, minibatch_count=4, gae_lambda=0.95,
        )


class PPO(Algorithm):
    def __init__(self, config: PPOConfig):
        super().__init__(config)
