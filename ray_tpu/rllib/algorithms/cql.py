"""CQL and IQL: offline RL for continuous control.

Reference analogs: ``rllib/algorithms/cql/`` (Conservative Q-Learning —
SAC-style twin critics plus a conservative penalty that pushes Q down on
out-of-distribution actions and up on dataset actions) and the IQL
capability of the reference's offline stack (Implicit Q-Learning: expectile
value regression + advantage-weighted policy extraction; no OOD action
queries at all). Both consume logged (s, a, r, s', done) transitions —
episodes in the MARWIL format — and need no environment except for optional
evaluation rollouts.

TPU shape: each algorithm's whole update (critics + value + policy [+
targets]) is ONE jitted program over replay minibatches; offline data sits
in host numpy and minibatches stream in per step.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib import module as rl_module
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.marwil import _NullRunnerGroup


def episodes_to_sarsd(episodes: List[Dict[str, Any]]) -> Dict[str, np.ndarray]:
    """Flatten episodes into (s, a, r, s', done) transition arrays.
    The terminal flag marks true environment termination; episode ends are
    always transition boundaries."""
    obs, act, rew, nobs, done = [], [], [], [], []
    for ep in episodes:
        o = np.asarray(ep["obs"], np.float32)
        a = np.asarray(ep["actions"], np.float32)
        r = np.asarray(ep["rewards"], np.float32)
        T = len(r)
        if o.shape[0] < T + 1:
            # no trailing observation logged: drop the final transition
            T = T - 1
            if T <= 0:
                continue
        obs.append(o[:T])
        nobs.append(o[1 : T + 1])
        act.append(a[:T])
        rew.append(r[:T])
        d = np.zeros(T, np.float32)
        if bool(ep.get("terminated", True)):
            d[-1] = 1.0
        done.append(d)
    return {
        "obs": np.concatenate(obs),
        "actions": np.concatenate(act),
        "rewards": np.concatenate(rew),
        "next_obs": np.concatenate(nobs),
        "dones": np.concatenate(done),
    }


class _OfflineBase(Algorithm):
    """Shared bring-up for offline continuous-control algorithms."""

    def _load_offline(self, config):
        episodes = list(config.episodes or [])
        if config.dataset is not None:
            episodes.extend(config.dataset.take_all())
        if not episodes:
            raise ValueError(
                f"{config.algo_name} needs offline data: "
                "config.offline_data(episodes=...) or (dataset=...)"
            )
        self.data = episodes_to_sarsd(episodes)
        self._n = self.data["obs"].shape[0]
        if config.env is not None or config.env_creator is not None:
            self._init_common(config)
        else:
            self.iteration = 0
            self._total_env_steps = 0
            self._last_step_count = 0
            self._recent_returns = []
            self.module_config = rl_module.RLModuleConfig(
                obs_dim=self.data["obs"].shape[1],
                action_dim=self.data["actions"].shape[1],
                discrete=False,
            )
        if self.module_config.discrete:
            raise ValueError(
                f"{config.algo_name} requires continuous actions"
            )

    def _make_runner_group(self, config):
        import jax

        if config.env is not None or config.env_creator is not None:
            from ray_tpu.rllib.env_runner import EnvRunnerGroup

            self.runner_group = EnvRunnerGroup(
                config.get_env_creator(), config.num_env_runners,
                config.num_envs_per_runner, config.rollout_fragment_length,
                self.module_config, seed=config.seed,
                gamma=config.hp.gamma,
                env_to_module=config.env_to_module_connector,
                module_to_env=config.module_to_env_connector,
            )
            self.runner_group.sync_weights(jax.device_get(self.pi_params))
        else:
            self.runner_group = _NullRunnerGroup()

    def _minibatch(self, bs):
        import jax.numpy as jnp

        idx = self._rng.randint(0, self._n, bs)
        return {
            k: jnp.asarray(v[idx]) for k, v in self.data.items()
        }

    def _eval_rollout(self):
        import jax

        self.runner_group.sync_weights(jax.device_get(self.pi_params))
        frags = self.runner_group.sample()
        if frags:
            batch = self._build_batch(frags)
            self._record_env_steps(batch)
        else:
            self._last_step_count = 0

    def get_weights(self):
        import jax

        return jax.device_get(self.pi_params)

    def save(self, path: str) -> str:
        import os
        import pickle

        import jax

        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "algorithm_state.pkl"), "wb") as f:
            pickle.dump({
                "pi_params": jax.device_get(self.pi_params),
                "q_params": jax.device_get(self.q_params),
                "extra": jax.device_get(self._extra_state()),
                "iteration": self.iteration,
                "algo": self.config.algo_name,
            }, f)
        return path

    def restore(self, path: str):
        import os
        import pickle

        import jax
        import jax.numpy as jnp

        with open(os.path.join(path, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self.pi_params = jax.tree.map(jnp.asarray, state["pi_params"])
        self.q_params = jax.tree.map(jnp.asarray, state["q_params"])
        self._restore_extra(jax.tree.map(jnp.asarray, state["extra"]))
        self.iteration = state["iteration"]
        self.runner_group.sync_weights(jax.device_get(self.pi_params))

    def _extra_state(self):
        return {}

    def _restore_extra(self, extra):
        pass


# ------------------------------------------------------------------- IQL


class IQLConfig(AlgorithmConfig):
    algo_name = "iql"

    def __init__(self):
        super().__init__()
        self.training(lr=3e-4, gamma=0.99)
        self.learn_batch_size = 256
        self.updates_per_step = 32
        self.expectile = 0.8           # tau: V regresses toward upper Q
        self.awr_beta = 3.0            # advantage-weighted regression temp
        self.max_weight = 100.0
        self.tau = 0.005               # polyak for target critics
        self.critic_hidden = (128, 128)
        self.episodes: Optional[List[Dict[str, Any]]] = None
        self.dataset = None

    def offline_data(self, *, episodes=None, dataset=None):
        self.episodes = episodes
        self.dataset = dataset
        return self

    def build_algo(self) -> "IQL":
        return IQL(self)


class IQL(_OfflineBase):
    """Implicit Q-Learning (expectile value + AWR policy). Never queries Q
    at out-of-distribution actions — the defining property."""

    def __init__(self, config: IQLConfig):
        import jax
        import jax.numpy as jnp
        import optax

        self.config = config
        self._load_offline(config)
        cfg = self.module_config
        hp = config.hp
        A = cfg.action_dim

        key = jax.random.PRNGKey(config.seed)
        k_pi, k_q1, k_q2, k_v = jax.random.split(key, 4)
        self.pi_params = rl_module.init_params(cfg, k_pi)
        q_sizes = [cfg.obs_dim + A, *config.critic_hidden, 1]
        v_sizes = [cfg.obs_dim, *config.critic_hidden, 1]
        self.q_params = {
            "q1": rl_module._init_mlp(k_q1, q_sizes, cfg.dtype),
            "q2": rl_module._init_mlp(k_q2, q_sizes, cfg.dtype),
        }
        self.q_target = jax.tree.map(jnp.copy, self.q_params)
        self.v_params = rl_module._init_mlp(k_v, v_sizes, cfg.dtype)

        self.pi_opt = optax.adam(hp.lr)
        self.q_opt = optax.adam(hp.lr)
        self.v_opt = optax.adam(hp.lr)
        self.pi_os = self.pi_opt.init(self.pi_params)
        self.q_os = self.q_opt.init(self.q_params)
        self.v_os = self.v_opt.init(self.v_params)
        self._rng = np.random.RandomState(config.seed)

        gamma, tau = hp.gamma, config.tau
        expectile, beta = config.expectile, config.awr_beta
        max_w = config.max_weight

        def q_value(qp, obs, act):
            x = jnp.concatenate([obs, act], -1)
            q1 = rl_module._mlp(qp["q1"], x)[..., 0]
            q2 = rl_module._mlp(qp["q2"], x)[..., 0]
            return q1, q2

        def update(pi_p, q_p, q_t, v_p, pi_os, q_os, v_os, batch):
            # 1) V: expectile regression toward min target-Q at DATA actions
            tq1, tq2 = q_value(q_t, batch["obs"], batch["actions"])
            tq = jax.lax.stop_gradient(jnp.minimum(tq1, tq2))

            def v_loss_fn(vp):
                v = rl_module._mlp(vp, batch["obs"])[..., 0]
                diff = tq - v
                w = jnp.where(diff > 0, expectile, 1.0 - expectile)
                return jnp.mean(w * diff ** 2), v

            (v_loss, v), v_grads = jax.value_and_grad(
                v_loss_fn, has_aux=True
            )(v_p)
            v_up, v_os = self.v_opt.update(v_grads, v_os, v_p)
            v_p = optax.apply_updates(v_p, v_up)

            # 2) Q: bellman target r + gamma (1-d) V(s')
            vs_next = rl_module._mlp(v_p, batch["next_obs"])[..., 0]
            target = jax.lax.stop_gradient(
                batch["rewards"]
                + gamma * (1.0 - batch["dones"]) * vs_next
            )

            def q_loss_fn(qp):
                q1, q2 = q_value(qp, batch["obs"], batch["actions"])
                return jnp.mean((q1 - target) ** 2) \
                    + jnp.mean((q2 - target) ** 2)

            q_loss, q_grads = jax.value_and_grad(q_loss_fn)(q_p)
            q_up, q_os = self.q_opt.update(q_grads, q_os, q_p)
            q_p = optax.apply_updates(q_p, q_up)

            # 3) policy: advantage-weighted regression onto data actions
            adv = jax.lax.stop_gradient(tq - v)
            w = jnp.minimum(jnp.exp(beta * adv), max_w)

            def pi_loss_fn(pp):
                logp, _, _ = rl_module.logp_entropy_value(
                    pp, cfg, batch["obs"], batch["actions"]
                )
                return -jnp.mean(w * logp)

            pi_loss, pi_grads = jax.value_and_grad(pi_loss_fn)(pi_p)
            pi_up, pi_os = self.pi_opt.update(pi_grads, pi_os, pi_p)
            pi_p = optax.apply_updates(pi_p, pi_up)

            q_t = jax.tree.map(
                lambda t, o: (1 - tau) * t + tau * o, q_t, q_p
            )
            return (pi_p, q_p, q_t, v_p, pi_os, q_os, v_os,
                    pi_loss, q_loss, v_loss)

        self._update = jax.jit(update)
        self._make_runner_group(config)

    def training_step(self) -> Dict[str, float]:
        pi_ls, q_ls, v_ls = [], [], []
        bs = min(self.config.learn_batch_size, self._n)
        for _ in range(self.config.updates_per_step):
            mb = self._minibatch(bs)
            (self.pi_params, self.q_params, self.q_target, self.v_params,
             self.pi_os, self.q_os, self.v_os, pi_l, q_l, v_l
             ) = self._update(
                self.pi_params, self.q_params, self.q_target,
                self.v_params, self.pi_os, self.q_os, self.v_os, mb,
            )
            pi_ls.append(float(pi_l))
            q_ls.append(float(q_l))
            v_ls.append(float(v_l))
        self._eval_rollout()
        return {
            "policy_loss": float(np.mean(pi_ls)),
            "critic_loss": float(np.mean(q_ls)),
            "value_loss": float(np.mean(v_ls)),
            "total_loss": float(np.mean(pi_ls) + np.mean(q_ls)),
            "num_offline_transitions": float(self._n),
        }

    def _extra_state(self):
        return {
            "v_params": self.v_params, "q_target": self.q_target,
            "pi_os": self.pi_os, "q_os": self.q_os, "v_os": self.v_os,
        }

    def _restore_extra(self, extra):
        self.v_params = extra["v_params"]
        self.q_target = extra["q_target"]
        # Adam moments resume with the params: a restore must continue the
        # same trajectory, not cold-start the optimizer.
        self.pi_os = extra["pi_os"]
        self.q_os = extra["q_os"]
        self.v_os = extra["v_os"]


# ------------------------------------------------------------------- CQL


class CQLConfig(AlgorithmConfig):
    algo_name = "cql"

    def __init__(self):
        super().__init__()
        self.training(lr=3e-4, gamma=0.99)
        self.learn_batch_size = 256
        self.updates_per_step = 32
        self.tau = 0.005
        self.alpha_entropy = 0.1       # fixed SAC entropy temperature
        self.cql_alpha = 1.0           # conservative penalty weight
        self.cql_num_actions = 8       # sampled actions for the logsumexp
        self.critic_hidden = (128, 128)
        self.episodes: Optional[List[Dict[str, Any]]] = None
        self.dataset = None

    def offline_data(self, *, episodes=None, dataset=None):
        self.episodes = episodes
        self.dataset = dataset
        return self

    def build_algo(self) -> "CQL":
        return CQL(self)


class CQL(_OfflineBase):
    """Conservative Q-Learning (reference: ``rllib/algorithms/cql``):
    SAC-style twin critics + logsumexp conservative penalty."""

    def __init__(self, config: CQLConfig):
        import dataclasses

        import jax
        import jax.numpy as jnp
        import optax

        self.config = config
        self._load_offline(config)
        self.module_config = dataclasses.replace(
            self.module_config, exploration="squashed_gaussian"
        )
        cfg = self.module_config
        hp = config.hp
        A = cfg.action_dim

        key = jax.random.PRNGKey(config.seed)
        k_pi, k_q1, k_q2 = jax.random.split(key, 3)
        self.pi_params = rl_module.init_params(cfg, k_pi)
        q_sizes = [cfg.obs_dim + A, *config.critic_hidden, 1]
        self.q_params = {
            "q1": rl_module._init_mlp(k_q1, q_sizes, cfg.dtype),
            "q2": rl_module._init_mlp(k_q2, q_sizes, cfg.dtype),
        }
        self.q_target = jax.tree.map(jnp.copy, self.q_params)
        self.pi_opt = optax.adam(hp.lr)
        self.q_opt = optax.adam(hp.lr)
        self.pi_os = self.pi_opt.init(self.pi_params)
        self.q_os = self.q_opt.init(self.q_params)
        self._rng = np.random.RandomState(config.seed)
        self._step_key = jax.random.PRNGKey(config.seed + 1)

        gamma, tau = hp.gamma, config.tau
        alpha = config.alpha_entropy
        cql_alpha = config.cql_alpha
        n_act = config.cql_num_actions

        def q_value(qp, obs, act):
            x = jnp.concatenate([obs, act], -1)
            q1 = rl_module._mlp(qp["q1"], x)[..., 0]
            q2 = rl_module._mlp(qp["q2"], x)[..., 0]
            return q1, q2

        def q_at_sampled(qp, obs, acts):
            # acts: [K, B, A]; returns per-critic [K, B]
            K = acts.shape[0]
            ob = jnp.broadcast_to(obs[None], (K,) + obs.shape)
            x = jnp.concatenate([ob, acts], -1).reshape(
                K * obs.shape[0], -1
            )
            q1 = rl_module._mlp(qp["q1"], x)[..., 0].reshape(K, -1)
            q2 = rl_module._mlp(qp["q2"], x)[..., 0].reshape(K, -1)
            return q1, q2

        def update(pi_p, q_p, q_t, pi_os, q_os, batch, rng):
            B = batch["obs"].shape[0]
            r_next, r_cur, r_unif = jax.random.split(rng, 3)

            # SAC target with entropy bonus at the next state
            mean_n, logstd_n = rl_module.squashed_gaussian_dist(
                pi_p, cfg, batch["next_obs"]
            )
            a_next, logp_next = rl_module.squashed_sample_logp(
                mean_n, logstd_n, r_next
            )
            tq1, tq2 = q_value(q_t, batch["next_obs"], a_next)
            target = jax.lax.stop_gradient(
                batch["rewards"] + gamma * (1.0 - batch["dones"]) * (
                    jnp.minimum(tq1, tq2) - alpha * logp_next
                )
            )

            # sampled actions for the conservative logsumexp:
            # uniform + current policy at s
            unif = jax.random.uniform(
                r_unif, (n_act, B, A), minval=-1.0, maxval=1.0
            )
            mean_c, logstd_c = rl_module.squashed_gaussian_dist(
                pi_p, cfg, batch["obs"]
            )
            pol = jnp.stack([
                rl_module.squashed_sample_logp(
                    mean_c, logstd_c, jax.random.fold_in(r_cur, i)
                )[0]
                for i in range(n_act)
            ])
            cand = jax.lax.stop_gradient(
                jnp.concatenate([unif, pol], axis=0)
            )

            def critic_loss(qp):
                q1, q2 = q_value(qp, batch["obs"], batch["actions"])
                bellman = jnp.mean((q1 - target) ** 2) \
                    + jnp.mean((q2 - target) ** 2)
                s1, s2 = q_at_sampled(qp, batch["obs"], cand)
                # push down on broad-action logsumexp, up on data actions
                cons = (
                    jnp.mean(jax.nn.logsumexp(s1, axis=0) - q1)
                    + jnp.mean(jax.nn.logsumexp(s2, axis=0) - q2)
                )
                return bellman + cql_alpha * cons, (bellman, cons)

            (q_loss, (bellman, cons)), q_grads = jax.value_and_grad(
                critic_loss, has_aux=True
            )(q_p)
            q_up, q_os = self.q_opt.update(q_grads, q_os, q_p)
            q_p = optax.apply_updates(q_p, q_up)

            # SAC actor on the offline batch
            def actor_loss(pp):
                mean, logstd = rl_module.squashed_gaussian_dist(
                    pp, cfg, batch["obs"]
                )
                a, logp = rl_module.squashed_sample_logp(
                    mean, logstd, r_cur
                )
                q1, q2 = q_value(q_p, batch["obs"], a)
                return jnp.mean(alpha * logp - jnp.minimum(q1, q2))

            pi_loss, pi_grads = jax.value_and_grad(actor_loss)(pi_p)
            pi_up, pi_os = self.pi_opt.update(pi_grads, pi_os, pi_p)
            pi_p = optax.apply_updates(pi_p, pi_up)

            q_t = jax.tree.map(
                lambda t, o: (1 - tau) * t + tau * o, q_t, q_p
            )
            return (pi_p, q_p, q_t, pi_os, q_os,
                    pi_loss, q_loss, bellman, cons)

        self._update = jax.jit(update)
        self._make_runner_group(config)

    def training_step(self) -> Dict[str, float]:
        import jax

        pi_ls, q_ls, bell, cons = [], [], [], []
        bs = min(self.config.learn_batch_size, self._n)
        for _ in range(self.config.updates_per_step):
            mb = self._minibatch(bs)
            self._step_key, sub = jax.random.split(self._step_key)
            (self.pi_params, self.q_params, self.q_target,
             self.pi_os, self.q_os, pi_l, q_l, b_l, c_l
             ) = self._update(
                self.pi_params, self.q_params, self.q_target,
                self.pi_os, self.q_os, mb, sub,
            )
            pi_ls.append(float(pi_l))
            q_ls.append(float(q_l))
            bell.append(float(b_l))
            cons.append(float(c_l))
        self._eval_rollout()
        return {
            "policy_loss": float(np.mean(pi_ls)),
            "critic_loss": float(np.mean(q_ls)),
            "bellman_loss": float(np.mean(bell)),
            "conservative_gap": float(np.mean(cons)),
            "total_loss": float(np.mean(q_ls)),
            "num_offline_transitions": float(self._n),
        }

    def _extra_state(self):
        return {
            "q_target": self.q_target,
            "pi_os": self.pi_os, "q_os": self.q_os,
        }

    def _restore_extra(self, extra):
        self.q_target = extra["q_target"]
        self.pi_os = extra["pi_os"]
        self.q_os = extra["q_os"]
