"""MARWIL and BC: offline RL from logged episodes.

Reference analog: ``rllib/algorithms/marwil/`` and ``rllib/algorithms/bc/``
(BC subclasses MARWIL with beta=0). MARWIL is advantage-weighted behavior
cloning: actions are imitated with weight exp(beta * advantage / c) where
the advantage is (monte-carlo return - V(s)) and c is a running scale
normalizer; beta=0 degenerates to plain behavior cloning. Offline data comes
from logged episodes (lists of dicts or a :class:`ray_tpu.data.Dataset`),
not env runners; an environment is optional and used only for evaluation
rollouts.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib import module as rl_module
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig


class _NullRunnerGroup:
    """Stands in when no evaluation env is configured."""

    def sample(self):
        return []

    def metrics(self):
        return []

    def sync_weights(self, params):
        pass

    def sync_connector_states(self):
        return {}

    def stop(self):
        pass


def episodes_to_transitions(
    episodes: List[Dict[str, Any]], gamma: float
) -> Dict[str, np.ndarray]:
    """Flatten episodes into {obs, actions, returns} with discounted
    monte-carlo returns per step (the MARWIL advantage target)."""
    all_obs, all_act, all_ret = [], [], []
    for ep in episodes:
        obs = np.asarray(ep["obs"], np.float32)
        act = np.asarray(ep["actions"])
        rew = np.asarray(ep["rewards"], np.float32)
        T = len(rew)
        ret = np.zeros(T, np.float32)
        acc = 0.0
        for t in range(T - 1, -1, -1):
            acc = rew[t] + gamma * acc
            ret[t] = acc
        all_obs.append(obs[:T])
        all_act.append(act[:T])
        all_ret.append(ret)
    return {
        "obs": np.concatenate(all_obs),
        "actions": np.concatenate(all_act),
        "returns": np.concatenate(all_ret),
    }


class MARWILConfig(AlgorithmConfig):
    algo_name = "marwil"

    def __init__(self):
        super().__init__()
        self.training(lr=1e-3, gamma=0.99)
        self.beta = 1.0                # 0 = BC
        self.vf_coeff = 1.0
        self.learn_batch_size = 256
        self.updates_per_step = 32
        self.moving_avg_coeff = 1e-2   # running normalizer for exp weights
        self.max_weight = 20.0
        self.episodes: Optional[List[Dict[str, Any]]] = None
        self.dataset = None            # ray_tpu.data.Dataset of episode rows
        self.evaluation_env = True     # rollout eval when an env is set

    def offline_data(self, *, episodes=None, dataset=None):
        """Provide logged episodes: a list of {obs, actions, rewards} dicts
        or a ray_tpu.data.Dataset whose rows are such episodes."""
        self.episodes = episodes
        self.dataset = dataset
        return self

    def build_algo(self) -> "MARWIL":
        return MARWIL(self)


class BCConfig(MARWILConfig):
    algo_name = "bc"

    def __init__(self):
        super().__init__()
        self.beta = 0.0

    def build_algo(self) -> "BC":
        return BC(self)


# BC is MARWIL with beta=0 (reference: rllib/algorithms/bc/bc.py)
class MARWIL(Algorithm):
    def __init__(self, config: MARWILConfig):
        import jax
        import jax.numpy as jnp
        import optax

        self.config = config
        episodes = list(config.episodes or [])
        if config.dataset is not None:
            episodes.extend(config.dataset.take_all())
        if not episodes:
            raise ValueError(
                "MARWIL/BC needs offline data: "
                "config.offline_data(episodes=...) or (dataset=...)"
            )
        self.data = episodes_to_transitions(episodes, config.hp.gamma)
        n = self.data["obs"].shape[0]

        # module config: from the eval env when given, else from the data
        if config.env is not None or config.env_creator is not None:
            self._init_common(config)
        else:
            self.iteration = 0
            self._total_env_steps = 0
            self._last_step_count = 0
            self._recent_returns = []
            acts = self.data["actions"]
            discrete = np.issubdtype(acts.dtype, np.integer)
            self.module_config = rl_module.RLModuleConfig(
                obs_dim=self.data["obs"].shape[1],
                action_dim=(
                    int(acts.max()) + 1 if discrete else acts.shape[1]
                ),
                discrete=discrete,
            )
        cfg = self.module_config
        hp = config.hp

        key = jax.random.PRNGKey(config.seed)
        self.params = rl_module.init_params(cfg, key)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(hp.grad_clip), optax.adam(hp.lr)
        )
        self.opt_state = self.optimizer.init(self.params)
        self.c_sq = jnp.float32(1.0)  # running mean of advantage^2
        self._rng = np.random.RandomState(config.seed)
        self._n = n

        beta = config.beta
        vf_coeff = config.vf_coeff
        ma = config.moving_avg_coeff
        max_w = config.max_weight

        def update(params, opt_state, c_sq, batch):
            def loss_fn(p):
                logp, _, value = rl_module.logp_entropy_value(
                    p, cfg, batch["obs"], batch["actions"]
                )
                if beta > 0:
                    adv = batch["returns"] - value
                    c_sq_new = c_sq + ma * (jnp.mean(adv ** 2) - c_sq)
                    w = jnp.exp(
                        beta * jax.lax.stop_gradient(adv)
                        / jnp.sqrt(c_sq_new + 1e-8)
                    )
                    w = jnp.minimum(w, max_w)
                    pi_loss = -jnp.mean(jax.lax.stop_gradient(w) * logp)
                    vf_loss = jnp.mean(adv ** 2)
                    total = pi_loss + vf_coeff * vf_loss
                else:
                    # BC: pure behavior cloning — no advantage weights and
                    # no value head training (reference: bc.py skips the
                    # value branch entirely)
                    pi_loss = -jnp.mean(logp)
                    vf_loss = jnp.float32(0.0)
                    c_sq_new = c_sq
                    total = pi_loss
                return total, (pi_loss, vf_loss, c_sq_new)

            (total, (pi_l, vf_l, c_sq_new)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            updates, opt_state = self.optimizer.update(
                grads, opt_state, params
            )
            params = optax.apply_updates(params, updates)
            return params, opt_state, c_sq_new, total, pi_l, vf_l

        self._update = jax.jit(update)

        if config.env is not None or config.env_creator is not None:
            from ray_tpu.rllib.env_runner import EnvRunnerGroup

            self.runner_group = EnvRunnerGroup(
                config.get_env_creator(), config.num_env_runners,
                config.num_envs_per_runner, config.rollout_fragment_length,
                self.module_config, seed=config.seed, gamma=hp.gamma,
                env_to_module=config.env_to_module_connector,
                module_to_env=config.module_to_env_connector,
            )
            self.runner_group.sync_weights(jax.device_get(self.params))
        else:
            self.runner_group = _NullRunnerGroup()

    # ---------------------------------------------------------------- train

    def training_step(self) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp

        losses, pi_ls, vf_ls = [], [], []
        bs = min(self.config.learn_batch_size, self._n)
        for _ in range(self.config.updates_per_step):
            idx = self._rng.randint(0, self._n, bs)
            mb = {
                "obs": jnp.asarray(self.data["obs"][idx]),
                "actions": jnp.asarray(self.data["actions"][idx]),
                "returns": jnp.asarray(self.data["returns"][idx]),
            }
            (self.params, self.opt_state, self.c_sq, total, pi_l, vf_l
             ) = self._update(self.params, self.opt_state, self.c_sq, mb)
            losses.append(float(total))
            pi_ls.append(float(pi_l))
            vf_ls.append(float(vf_l))
        # evaluation rollouts (when an env is configured)
        self.runner_group.sync_weights(jax.device_get(self.params))
        frags = self.runner_group.sample()
        if frags:
            batch = self._build_batch(frags)
            self._record_env_steps(batch)
        else:
            self._last_step_count = 0
        return {
            "total_loss": float(np.mean(losses)),
            "policy_loss": float(np.mean(pi_ls)),
            "vf_loss": float(np.mean(vf_ls)),
            "num_offline_transitions": float(self._n),
        }

    # ------------------------------------------------------------ lifecycle

    def get_weights(self):
        import jax

        return jax.device_get(self.params)

    def save(self, path: str) -> str:
        import os
        import pickle

        import jax

        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "algorithm_state.pkl"), "wb") as f:
            pickle.dump({
                "params": jax.device_get(self.params),
                "c_sq": float(self.c_sq),
                "iteration": self.iteration,
                "algo": self.config.algo_name,
            }, f)
        return path

    def restore(self, path: str):
        import os
        import pickle

        import jax
        import jax.numpy as jnp

        with open(os.path.join(path, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self.params = jax.tree.map(jnp.asarray, state["params"])
        self.c_sq = jnp.float32(state["c_sq"])
        self.iteration = state["iteration"]
        self.runner_group.sync_weights(jax.device_get(self.params))


class BC(MARWIL):
    """Behavior cloning = MARWIL with beta=0 (reference: bc.py)."""
