"""TQC: truncated quantile critics for continuous control.

Reference analog: ``rllib/algorithms/`` TQC (distributional SAC variant;
listed in the reference's algorithm roster). Off-policy maximum-entropy RL
like SAC, but each critic is distributional — it predicts M quantile
atoms of the return distribution — and the TD target pools the atoms of
all N target critics, sorts them, and drops the top ``d`` atoms per critic
before bootstrapping. Truncating the right tail of the pooled mixture is a
finer-grained overestimation control than SAC's min-of-two-scalars.

Loss is the quantile Huber regression of every predicted atom against every
kept target atom (taus at quantile midpoints). Actor and temperature updates
are SAC's, with Q(s,a) read as the mean over all critics' atoms.

The whole update (critics + actor + alpha + polyak) is one jitted program
over replay minibatches; exploration reuses the squashed-gaussian policy
head and the SAC replay buffer.
"""
from __future__ import annotations

from typing import Dict

from ray_tpu.rllib import module as rl_module
from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.algorithms.sac import ContinuousReplayBuffer, SACConfig


class TQCConfig(SACConfig):
    algo_name = "tqc"

    def __init__(self):
        super().__init__()
        self.n_critics = 2
        self.n_quantiles = 25
        # Atoms dropped from the TOP of the pooled target distribution,
        # counted per critic (paper/SB3 convention): total kept =
        # n_critics * (n_quantiles - top_quantiles_to_drop_per_net).
        self.top_quantiles_to_drop_per_net = 2

    def build_algo(self) -> "TQC":
        return TQC(self)


class TQC(Algorithm):
    def __init__(self, config: TQCConfig):
        import dataclasses

        import jax
        import jax.numpy as jnp
        import optax

        self._init_common(config)
        if self.module_config.discrete:
            raise ValueError(
                "TQC requires a continuous (Box) action space; "
                f"{config.env or config.env_creator} has a discrete one"
            )
        self.module_config = dataclasses.replace(
            self.module_config, exploration="squashed_gaussian"
        )
        cfg = self.module_config
        hp = config.hp
        A = cfg.action_dim
        N, M = config.n_critics, config.n_quantiles
        drop_total = config.top_quantiles_to_drop_per_net * N
        keep = N * M - drop_total
        if keep <= 0:
            raise ValueError(
                f"top_quantiles_to_drop_per_net={config.top_quantiles_to_drop_per_net} "
                f"drops every atom (n_critics={N}, n_quantiles={M})"
            )
        target_entropy = (
            config.target_entropy
            if config.target_entropy is not None else -float(A)
        )

        key = jax.random.PRNGKey(config.seed)
        k_pi, *k_qs = jax.random.split(key, 1 + N)
        self.pi_params = rl_module.init_params(cfg, k_pi)
        q_sizes = [cfg.obs_dim + A, *config.critic_hidden, M]
        # One stacked pytree: leaves have a leading [N] critic axis so a
        # single vmapped forward evaluates the whole ensemble on the MXU.
        self.q_params = jax.tree.map(
            lambda *leaves: jnp.stack(leaves),
            *[rl_module._init_mlp(k, q_sizes, cfg.dtype) for k in k_qs],
        )
        self.q_target = jax.tree.map(jnp.copy, self.q_params)
        self.log_alpha = jnp.log(jnp.float32(config.init_alpha))

        self.pi_opt = optax.adam(hp.lr)
        self.q_opt = optax.adam(hp.lr)
        self.alpha_opt = optax.adam(hp.lr)
        self.pi_opt_state = self.pi_opt.init(self.pi_params)
        self.q_opt_state = self.q_opt.init(self.q_params)
        self.alpha_opt_state = self.alpha_opt.init(self.log_alpha)

        self.buffer = ContinuousReplayBuffer(
            config.replay_capacity, cfg.obs_dim, A, seed=config.seed
        )
        self._update_key = jax.random.PRNGKey(config.seed + 1)

        gamma, tau = hp.gamma, config.tau
        # Quantile midpoints tau_i = (2i+1)/2M — the regression targets'
        # probability levels for each predicted atom.
        taus = (jnp.arange(M, dtype=jnp.float32) + 0.5) / M

        def atoms(qp, obs, act):
            """[batch, N, M] quantile atoms from the stacked ensemble."""
            x = jnp.concatenate([obs, act], -1)
            per_critic = jax.vmap(
                lambda layers: rl_module._mlp(layers, x)
            )(qp)                      # [N, batch, M]
            return per_critic.transpose(1, 0, 2)

        def quantile_huber(pred, target):
            """pred [B, N, M] vs target [B, K]: mean quantile Huber loss.

            Asymmetric |tau - 1{u<0}| weighting on a kappa=1 Huber kernel
            (QR-DQN form), averaged over atoms, critics, and targets.
            """
            u = target[:, None, None, :] - pred[..., None]   # [B, N, M, K]
            abs_u = jnp.abs(u)
            huber = jnp.where(abs_u <= 1.0, 0.5 * u * u, abs_u - 0.5)
            weight = jnp.abs(taus[None, None, :, None] - (u < 0.0))
            return jnp.mean(jnp.sum(weight * huber, axis=2))

        def update(pi_p, q_p, q_t, log_alpha, pi_os, q_os, a_os, batch, rng):
            k_next, k_pi_new = jax.random.split(rng)
            alpha = jnp.exp(log_alpha)

            # ---- target: pooled, sorted, top-truncated next-state atoms
            mean_n, logstd_n = rl_module.squashed_gaussian_dist(
                pi_p, cfg, batch["next_obs"]
            )
            a_next, logp_next = rl_module.squashed_sample_logp(
                mean_n, logstd_n, k_next
            )
            z_next = atoms(q_t, batch["next_obs"], a_next)   # [B, N, M]
            pooled = jnp.sort(z_next.reshape(z_next.shape[0], N * M), -1)
            kept = pooled[:, :keep]                          # drop the top
            target = batch["rewards"][:, None] + gamma * (
                1.0 - batch["dones"][:, None]
            ) * (kept - alpha * logp_next[:, None])
            target = jax.lax.stop_gradient(target)           # [B, keep]

            def critic_loss(q_p):
                pred = atoms(q_p, batch["obs"], batch["actions"])
                return quantile_huber(pred, target)

            c_loss, q_grads = jax.value_and_grad(critic_loss)(q_p)
            q_upd, q_os = self.q_opt.update(q_grads, q_os, q_p)
            import optax as _optax

            q_p = _optax.apply_updates(q_p, q_upd)

            # ---- actor: maximize E[mean-of-atoms Q - alpha * logp]
            def actor_loss(pi_p):
                mean, logstd = rl_module.squashed_gaussian_dist(
                    pi_p, cfg, batch["obs"]
                )
                a_new, logp = rl_module.squashed_sample_logp(
                    mean, logstd, k_pi_new
                )
                q_new = jnp.mean(atoms(q_p, batch["obs"], a_new), (-2, -1))
                return jnp.mean(alpha * logp - q_new), jnp.mean(logp)

            (a_loss, mean_logp), pi_grads = jax.value_and_grad(
                actor_loss, has_aux=True
            )(pi_p)
            pi_upd, pi_os = self.pi_opt.update(pi_grads, pi_os, pi_p)
            pi_p = _optax.apply_updates(pi_p, pi_upd)

            # ---- temperature (SAC)
            def alpha_loss(log_a):
                return -log_a * jax.lax.stop_gradient(
                    mean_logp + target_entropy
                )

            al_loss, a_grad = jax.value_and_grad(alpha_loss)(log_alpha)
            a_upd, a_os = self.alpha_opt.update(a_grad, a_os, log_alpha)
            log_alpha = _optax.apply_updates(log_alpha, a_upd)

            # ---- polyak target update
            q_t = jax.tree.map(
                lambda t, p: (1 - tau) * t + tau * p, q_t, q_p
            )
            metrics = {
                "critic_loss": c_loss,
                "actor_loss": a_loss,
                "alpha_loss": al_loss,
                "alpha": jnp.exp(log_alpha),
                "entropy": -mean_logp,
            }
            return pi_p, q_p, q_t, log_alpha, pi_os, q_os, a_os, metrics

        self._update = jax.jit(update)

        from ray_tpu.rllib.env_runner import EnvRunnerGroup

        self.runner_group = EnvRunnerGroup(
            config.get_env_creator(), config.num_env_runners,
            config.num_envs_per_runner, config.rollout_fragment_length,
            self.module_config, seed=config.seed, gamma=hp.gamma,
            env_to_module=config.env_to_module_connector,
            module_to_env=config.module_to_env_connector,
        )
        self.runner_group.sync_weights(jax.device_get(self.pi_params))

    # ---------------------------------------------------------------- train

    def training_step(self) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp

        fragments = self.runner_group.sample()
        if not fragments:
            self._last_step_count = 0
            return {"num_healthy_runners": 0}
        batch = self._build_batch(fragments)
        self.buffer.add_fragments(batch)
        self._record_env_steps(batch)

        metrics: Dict[str, float] = {"replay_size": float(self.buffer.size)}
        if self.buffer.size >= self.config.min_replay_size:
            last = {}
            for _ in range(self.config.updates_per_step):
                self._update_key, k = jax.random.split(self._update_key)
                mb = {
                    k2: jnp.asarray(v)
                    for k2, v in self.buffer.sample(
                        self.config.learn_batch_size
                    ).items()
                }
                (self.pi_params, self.q_params, self.q_target,
                 self.log_alpha, self.pi_opt_state, self.q_opt_state,
                 self.alpha_opt_state, last) = self._update(
                    self.pi_params, self.q_params, self.q_target,
                    self.log_alpha, self.pi_opt_state, self.q_opt_state,
                    self.alpha_opt_state, mb, k,
                )
            metrics.update({k: float(v) for k, v in last.items()})
            metrics["total_loss"] = metrics.get("critic_loss", 0.0)
        self.runner_group.sync_weights(jax.device_get(self.pi_params))
        return metrics

    # ------------------------------------------------------------ lifecycle

    def get_weights(self):
        import jax

        return jax.device_get(self.pi_params)

    def save(self, path: str) -> str:
        import os
        import pickle

        import jax

        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "algorithm_state.pkl"), "wb") as f:
            pickle.dump({
                "pi_params": jax.device_get(self.pi_params),
                "q_params": jax.device_get(self.q_params),
                "q_target": jax.device_get(self.q_target),
                "log_alpha": float(self.log_alpha),
                "iteration": self.iteration,
                "total_env_steps": self._total_env_steps,
                "algo": "tqc",
            }, f)
        return path

    def restore(self, path: str):
        import os
        import pickle

        import jax
        import jax.numpy as jnp

        with open(os.path.join(path, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        as_jnp = lambda t: jax.tree.map(jnp.asarray, t)  # noqa: E731
        self.pi_params = as_jnp(state["pi_params"])
        self.q_params = as_jnp(state["q_params"])
        self.q_target = as_jnp(state["q_target"])
        self.log_alpha = jnp.float32(state["log_alpha"])
        self.iteration = state["iteration"]
        self._total_env_steps = state.get("total_env_steps", 0)
        self.runner_group.sync_weights(jax.device_get(self.pi_params))
