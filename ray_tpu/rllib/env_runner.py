"""Env runners: parallel episode collection actors.

Reference analog: ``rllib/env/single_agent_env_runner.py`` (gymnasium vector
envs sampled with the current module weights) + ``env_runner_group.py:70``
(actor group with healthy-only foreach and restarts).

Design: runners are plain actors that hold N independent gymnasium envs and a
jitted CPU policy forward; they return fixed-length rollout fragments as
numpy struct-of-arrays with a bootstrap value per env — exactly what the
jitted learner consumes with static shapes (no ragged episodes on device).
Vectorization is manual (reset-on-done per env) rather than gymnasium's
vector autoreset: the 1.x "reset happens on next step" semantics silently
corrupts fragment boundaries, and N small envs stepped in a loop is not the
bottleneck (policy inference is batched across envs).
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rllib import module as rl_module


class SingleAgentEnvRunner:
    """Collects rollout fragments from num_envs copies of one env."""

    def __init__(self, env_creator: Callable[[], Any], num_envs: int,
                 fragment_len: int, module_config: dict, seed: int = 0,
                 gamma: float = 0.99, env_to_module: Optional[Callable] = None,
                 module_to_env: Optional[Callable] = None):
        import jax

        self.envs = [env_creator() for _ in range(num_envs)]
        self.num_envs = num_envs
        self.fragment_len = fragment_len
        self.gamma = gamma
        self.config = rl_module.RLModuleConfig(**module_config)
        # Connector pipelines (reference: ConnectorV2 env_to_module /
        # module_to_env slots). Factories (zero-arg callables) so pipelines
        # pickle across the actor boundary and each runner owns its state.
        self.env_to_module = env_to_module() if env_to_module else None
        self.module_to_env = module_to_env() if module_to_env else None
        # Built-in action-bounds handling stays on unless the user pipeline
        # declares that it maps to env bounds itself: a module_to_env with
        # only e.g. a ClipActions stage must not silently disable the
        # squashed-gaussian [-1,1]->bounds rescale (raw tanh actions in a
        # differently-bounded env corrupt training without erroring).
        # Detection is by the ConnectorV2 rescales_actions/clips_actions
        # declarations, so custom bound-mapping stages can opt out of the
        # builtin by setting them (see connectors.ConnectorV2 docstring).
        stages: List[Any] = []
        if self.module_to_env is not None:
            stages = list(getattr(
                self.module_to_env, "connectors", [self.module_to_env]
            ))
        self._pipeline_rescales = any(
            getattr(c, "rescales_actions", False) for c in stages
        )
        self._pipeline_bounds = self._pipeline_rescales or any(
            getattr(c, "clips_actions", False) for c in stages
        )
        self.params = None
        self.rng = jax.random.PRNGKey(seed)
        self._sample_fn = jax.jit(
            lambda p, obs, rng: rl_module.sample_action(p, self.config, obs, rng)
        )
        self._value_fn = jax.jit(
            lambda p, obs: rl_module.forward_value(p, self.config, obs)
        )
        self.obs = np.stack([
            np.asarray(env.reset(seed=seed * 10_000 + i)[0], np.float32).ravel()
            for i, env in enumerate(self.envs)
        ])
        # episode-return bookkeeping for metrics
        self._ep_return = np.zeros(num_envs)
        self._ep_len = np.zeros(num_envs, np.int64)
        self._completed: List[tuple] = []
        self._total_steps = 0
        # Dones of the previous step, persisted ACROSS sample() calls: an
        # episode ending on a fragment's last step must still reset
        # stateful connectors (FrameStack) at the next fragment's start.
        self._last_dones: Optional[np.ndarray] = None

    def set_weights(self, params):
        self.params = params

    def get_weights(self):
        return self.params

    def sample(self) -> Dict[str, np.ndarray]:
        """One fragment: arrays of shape [T, N, ...] plus bootstrap values.

        Fragments cut across episode boundaries (dones mark the cuts); the
        learner computes GAE/V-trace with per-step done masks and the [N]
        bootstrap value of the final observation.
        """
        import jax

        assert self.params is not None, "set_weights before sample"
        T, N = self.fragment_len, self.num_envs
        obs_buf = None  # allocated after the first transform (connectors
        # like FrameStack change the module-side obs dim)
        act_dtype = np.int32 if self.config.discrete else np.float32
        act_shape = (T, N) if self.config.discrete else (T, N, self.config.action_dim)
        act_buf = np.empty(act_shape, act_dtype)
        rew_buf = np.empty((T, N), np.float32)
        done_buf = np.empty((T, N), np.float32)
        trunc_buf = np.zeros((T, N), np.float32)
        logp_buf = np.empty((T, N), np.float32)
        val_buf = np.empty((T, N), np.float32)

        for t in range(T):
            self.rng, k = jax.random.split(self.rng)
            mobs = self.obs
            if self.env_to_module is not None:
                mobs = np.asarray(self.env_to_module(
                    {"obs": self.obs}, dones=self._last_dones
                )["obs"], np.float32)
            if obs_buf is None:
                obs_buf = np.empty((T, N, mobs.shape[1]), np.float32)
            action, logp, value = self._sample_fn(self.params, mobs, k)
            action = np.asarray(action)
            obs_buf[t] = mobs
            act_buf[t] = action
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(value)
            env_actions = action
            if self.module_to_env is not None:
                env_actions = np.asarray(
                    self.module_to_env({"actions": action})["actions"]
                )
            for i, env in enumerate(self.envs):
                a = env_actions[i]
                if not self.config.discrete:
                    low = env.action_space.low
                    high = env.action_space.high
                    if self.config.exploration == "squashed_gaussian":
                        # SAC: tanh actions live in [-1, 1]; rescale to the
                        # env bounds (the buffer keeps the policy-space
                        # action from act_buf, not this env-space one).
                        # Applies even under a user module_to_env pipeline
                        # unless that pipeline has its own RescaleActions.
                        if not self._pipeline_rescales:
                            a = low + (a + 1.0) * 0.5 * (high - low)
                    elif not self._pipeline_bounds:
                        a = np.clip(a, low, high)
                nobs, rew, term, trunc, _ = env.step(
                    a if not self.config.discrete else int(a)
                )
                self._ep_return[i] += float(rew)
                self._ep_len[i] += 1
                rew_buf[t, i] = rew
                done = term or trunc
                done_buf[t, i] = float(done)
                if trunc and not term:
                    trunc_buf[t, i] = 1.0
                    # Time-limit truncation is not a true terminal: fold the
                    # tail value into the reward (partial bootstrap), then
                    # treat the step as done for advantage estimation. NOT
                    # for squashed_gaussian (SAC): its vf head is untrained,
                    # so the fold would bake random-network output into
                    # replay rewards — SAC instead drops truncation-boundary
                    # transitions via the truncateds array.
                    if self.config.exploration != "squashed_gaussian":
                        vobs = np.asarray(nobs, np.float32).ravel()[None, :]
                        if self.env_to_module is not None:
                            # training=False: a one-off value probe must
                            # not update running normalizer statistics
                            vobs = np.asarray(self.env_to_module(
                                {"obs": vobs}, training=False
                            )["obs"], np.float32)
                        fv = self._value_fn(self.params, vobs)
                        rew_buf[t, i] += self.gamma * float(np.asarray(fv)[0])
                if done:
                    self._completed.append(
                        (self._ep_return[i], int(self._ep_len[i]))
                    )
                    self._ep_return[i] = 0.0
                    self._ep_len[i] = 0
                    nobs = env.reset()[0]
                self.obs[i] = np.asarray(nobs, np.float32).ravel()
            self._last_dones = done_buf[t]  # FrameStack resets next step
        fobs = self.obs
        if self.env_to_module is not None:
            # Same transform the module saw during the fragment; a one-off
            # probe, so it must not update normalizer statistics. For
            # FrameStack this treats the frame as a fresh stack — the done
            # columns ARE fresh, and live columns only matter through the
            # bootstrap value, where the approximation is standard.
            fobs = np.asarray(self.env_to_module(
                {"obs": self.obs}, training=False
            )["obs"], np.float32)
        bootstrap = np.asarray(self._value_fn(self.params, fobs))
        self._total_steps += T * N
        return {
            "obs": obs_buf, "actions": act_buf, "rewards": rew_buf,
            "dones": done_buf, "truncateds": trunc_buf,
            "logp": logp_buf, "values": val_buf,
            "bootstrap_value": bootstrap,
        }

    def get_connector_state(self) -> Dict[str, Any]:
        if self.env_to_module is None:
            return {}
        return self.env_to_module.get_state()

    def set_connector_state(self, state: Dict[str, Any]) -> None:
        if self.env_to_module is not None and state:
            self.env_to_module.set_state(state)

    def metrics(self) -> Dict[str, Any]:
        completed, self._completed = self._completed, []
        returns = [r for r, _ in completed]
        lengths = [l for _, l in completed]
        return {
            "num_episodes": len(completed),
            "episode_returns": returns,
            "episode_lengths": lengths,
            "total_steps": self._total_steps,
        }

    def ping(self) -> bool:
        return True


class EnvRunnerGroup:
    """Actor group of env runners with healthy-only foreach + restart.

    Reference analog: ``rllib/env/env_runner_group.py`` (foreach_env_runner
    with healthy filtering; ``restore_env_runners`` respawns lost actors).
    """

    def __init__(self, env_creator, num_runners: int, num_envs_per_runner: int,
                 fragment_len: int, module_config: rl_module.RLModuleConfig,
                 seed: int = 0, gamma: float = 0.99,
                 env_to_module: Optional[Callable] = None,
                 module_to_env: Optional[Callable] = None,
                 runner_cls: Optional[type] = None):
        import ray_tpu

        cls = runner_cls or SingleAgentEnvRunner
        mc = (dict(module_config.__dict__)
              if hasattr(module_config, "__dict__") else dict(module_config))
        self._make = lambda idx: ray_tpu.remote(cls).options(
            name=f"env_runner_{idx}_{time.monotonic_ns()}", num_cpus=1
        ).remote(
            env_creator, num_envs_per_runner, fragment_len,
            mc, seed + 1000 * idx, gamma,
            env_to_module, module_to_env,
        )
        self.runners = [self._make(i) for i in range(num_runners)]
        self._weights = None
        # Local template pipeline: holds the merged state and provides the
        # per-connector merge_states implementations.
        self._connector_template = env_to_module() if env_to_module else None

    def sync_weights(self, params):
        import ray_tpu

        self._weights = params
        ray_tpu.get([r.set_weights.remote(params) for r in self.runners])

    def sample(self) -> List[Dict[str, np.ndarray]]:
        """Parallel fragment collection; dead runners are respawned (with the
        last-synced weights) and skipped this round."""
        import ray_tpu

        refs = [(i, r.sample.remote()) for i, r in enumerate(self.runners)]
        out = []
        dead = []
        for i, ref in refs:
            try:
                out.append(ray_tpu.get(ref, timeout=120))
            except Exception:
                dead.append(i)
        for i in dead:
            self.runners[i] = self._make(i)
            if self._weights is not None:
                try:
                    ray_tpu.get(
                        self.runners[i].set_weights.remote(self._weights),
                        timeout=60,
                    )
                except Exception:
                    pass
        return out

    def sync_connector_states(self) -> Dict[str, Any]:
        """Pull per-runner connector states, merge (count-weighted moment
        merge for MeanStdFilter etc.), broadcast the result — the
        reference's merge_env_runner_states flow. Returns the merged state
        (e.g. for a learner-side copy of the pipeline)."""
        import ray_tpu

        tpl = self._connector_template
        if tpl is None:
            return {}
        refs = [r.get_connector_state.remote() for r in self.runners]
        states = []
        for ref in refs:
            try:
                s = ray_tpu.get(ref, timeout=30)
                if s:
                    states.append(s)
            except Exception:
                pass
        if not states:
            return {}
        from ray_tpu.rllib.connectors import ConnectorPipelineV2

        if isinstance(tpl, ConnectorPipelineV2):
            merged = tpl.merge_states_from(states)
        else:
            merged = type(tpl).merge_states(states)
            tpl.set_state(merged)
        refs = [r.set_connector_state.remote(merged) for r in self.runners]
        for ref in refs:
            try:
                ray_tpu.get(ref, timeout=30)
            except Exception:
                pass
        return merged

    def metrics(self) -> List[Dict[str, Any]]:
        import ray_tpu

        out = []
        for r in self.runners:
            try:
                out.append(ray_tpu.get(r.metrics.remote(), timeout=30))
            except Exception:
                pass
        return out

    def stop(self):
        import ray_tpu

        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
