"""Algorithm: the RL training driver (config builder + train loop).

Reference analogs: ``rllib/algorithms/algorithm.py:208`` (Algorithm as a Tune
Trainable; ``step`` :1168, default ``training_step`` :2289) and
``algorithm_config.py`` (builder pattern: .environment/.env_runners/
.training). One Algorithm = an EnvRunnerGroup of sampling actors + a local
SPMD Learner; training_step = parallel sample → learner update → weight
broadcast, the same loop shape as the reference's new API stack.
"""
from __future__ import annotations

import copy
import os
import pickle
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rllib import module as rl_module
from ray_tpu.rllib.env_runner import EnvRunnerGroup
from ray_tpu.rllib.learner import Learner, LearnerHyperparams


def _default_env_creator(env_name: str) -> Callable[[], Any]:
    def create():
        import gymnasium as gym

        return gym.make(env_name)

    return create


class AlgorithmConfig:
    """Builder (reference: ``rllib/algorithms/algorithm_config.py``)."""

    algo_name = "base"

    def __init__(self):
        self.env: Optional[str] = None
        self.env_creator: Optional[Callable[[], Any]] = None
        self.num_env_runners = 2
        self.num_envs_per_runner = 4
        self.rollout_fragment_length = 64
        self.seed = 0
        self.mesh = None  # jax Mesh for the learner SPMD step (data axis)
        self.hp = LearnerHyperparams()
        # ConnectorV2 pipeline factories (zero-arg callables returning a
        # ConnectorPipelineV2 / ConnectorV2); see ray_tpu/rllib/connectors.py
        self.env_to_module_connector: Optional[Callable] = None
        self.module_to_env_connector: Optional[Callable] = None

    # builder sections -----------------------------------------------------

    def environment(self, env: Optional[str] = None, *,
                    env_creator: Optional[Callable[[], Any]] = None):
        self.env = env
        if env_creator is not None:
            self.env_creator = env_creator
        return self

    def env_runners(self, *, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None,
                    env_to_module_connector: Optional[Callable] = None,
                    module_to_env_connector: Optional[Callable] = None):
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if env_to_module_connector is not None:
            self.env_to_module_connector = env_to_module_connector
        if module_to_env_connector is not None:
            self.module_to_env_connector = module_to_env_connector
        return self

    def training(self, **kwargs):
        hp = self.hp.__dict__ | kwargs
        unknown = set(hp) - set(LearnerHyperparams().__dict__)
        if unknown:
            raise ValueError(f"unknown training params: {sorted(unknown)}")
        self.hp = LearnerHyperparams(**hp)
        return self

    def debugging(self, *, seed: Optional[int] = None):
        if seed is not None:
            self.seed = seed
        return self

    def learners(self, *, mesh=None):
        self.mesh = mesh
        return self

    def copy(self) -> "AlgorithmConfig":
        c = copy.copy(self)
        return c

    def build_algo(self) -> "Algorithm":
        return Algorithm(self)

    def get_env_creator(self) -> Callable[[], Any]:
        if self.env_creator is not None:
            return self.env_creator
        if self.env is None:
            raise ValueError("config.environment(env=...) not set")
        return _default_env_creator(self.env)


class Algorithm:
    """Train loop driver; Tune-compatible via ``as_trainable``."""

    def __init__(self, config: AlgorithmConfig):
        self._init_common(config)
        self.learner = Learner(
            config.algo_name, self.module_config, config.hp,
            seed=config.seed, mesh=config.mesh,
        )
        self.runner_group = EnvRunnerGroup(
            config.get_env_creator(), config.num_env_runners,
            config.num_envs_per_runner, config.rollout_fragment_length,
            self.module_config, seed=config.seed, gamma=config.hp.gamma,
            env_to_module=config.env_to_module_connector,
            module_to_env=config.module_to_env_connector,
        )
        self.runner_group.sync_weights(self.learner.get_weights())

    def _init_common(self, config: AlgorithmConfig):
        """Bookkeeping shared by every algorithm (subclasses that build
        their own learner/runners call this instead of __init__)."""
        self.config = config
        creator = config.get_env_creator()
        probe_env = creator()
        self.module_config = rl_module.module_config_for_env(probe_env)
        probe_env.close()
        self.iteration = 0
        self._total_env_steps = 0
        self._last_step_count = 0
        self._recent_returns: List[float] = []

    # ---------------------------------------------------------------- train

    def _build_batch(self, fragments) -> Dict[str, np.ndarray]:
        """Concat fragments along the env axis: [T, N_total, ...] — one
        static-shaped learner batch per step. bootstrap_value is [N]."""
        return {
            k: np.concatenate([f[k] for f in fragments], axis=-1)
            if fragments[0][k].ndim == 1
            else np.concatenate([f[k] for f in fragments], axis=1)
            for k in fragments[0]
        }

    def training_step(self) -> Dict[str, float]:
        fragments = self.runner_group.sample()
        if not fragments:
            self._last_step_count = 0  # nothing sampled this iteration
            return {"num_healthy_runners": 0}
        batch = self._build_batch(fragments)
        metrics = self.learner.update(batch)
        self.runner_group.sync_weights(self.learner.get_weights())
        self._record_env_steps(batch)
        return metrics

    def _record_env_steps(self, batch):
        steps = batch["rewards"].shape[0] * batch["rewards"].shape[1]
        self._total_env_steps += steps
        self._last_step_count = steps

    def train(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        metrics = self.training_step()
        # Merge + rebroadcast stateful connector statistics (MeanStdFilter
        # etc.) so every runner normalizes identically next iteration.
        # getattr: custom runner groups (multi-agent shim) predate the hook.
        sync_conn = getattr(self.runner_group, "sync_connector_states", None)
        if sync_conn is not None:
            sync_conn()
        self.iteration += 1
        ep_returns: List[float] = []
        num_episodes = 0
        for m in self.runner_group.metrics():
            ep_returns.extend(m["episode_returns"])
            num_episodes += m["num_episodes"]
        self._recent_returns.extend(ep_returns)
        self._recent_returns = self._recent_returns[-100:]
        mean_ret = (
            float(np.mean(self._recent_returns))
            if self._recent_returns else float("nan")
        )
        dt = time.perf_counter() - t0
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": mean_ret,
            "num_episodes": num_episodes,
            "num_env_steps_sampled_lifetime": self._total_env_steps,
            # actually-sampled steps this iteration (dead runners excluded)
            "env_steps_per_sec": self._last_step_count / max(dt, 1e-9),
            **metrics,
        }

    # ------------------------------------------------------------ lifecycle

    def save(self, path: str) -> str:
        os.makedirs(path, exist_ok=True)
        state = {
            "learner": self.learner.state(),
            "iteration": self.iteration,
            "total_env_steps": self._total_env_steps,
            "module_config": self.module_config.__dict__,
            "algo": self.config.algo_name,
        }
        with open(os.path.join(path, "algorithm_state.pkl"), "wb") as f:
            pickle.dump(state, f)
        return path

    def restore(self, path: str):
        with open(os.path.join(path, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self.learner.restore(state["learner"])
        self.iteration = state["iteration"]
        self._total_env_steps = state["total_env_steps"]
        self.runner_group.sync_weights(self.learner.get_weights())

    def stop(self):
        self.runner_group.stop()

    def get_weights(self):
        return self.learner.get_weights()

    # ------------------------------------------------------------- tune glue

    @classmethod
    def from_config_dict(cls, config_cls, overrides: Dict[str, Any]):
        cfg = config_cls()
        if "env" in overrides:
            cfg.environment(overrides["env"])
        hp_keys = set(LearnerHyperparams().__dict__)
        cfg.training(**{k: v for k, v in overrides.items() if k in hp_keys})
        return cfg.build_algo()


def make_trainable(config: AlgorithmConfig, stop_iters: int = 10,
                   stop_reward: Optional[float] = None):
    """Wrap an AlgorithmConfig for the Tune layer: a train_fn that builds the
    algo from a trial's hyperparams and reports per-iteration metrics
    (reference: Algorithm registered as a Tune Trainable)."""

    def trainable(trial_config: Dict[str, Any]):
        from ray_tpu import train as rt_train

        cfg = config.copy()
        hp_keys = set(LearnerHyperparams().__dict__)
        overrides = {k: v for k, v in trial_config.items() if k in hp_keys}
        if overrides:
            cfg.training(**overrides)
        algo = cfg.build_algo()
        try:
            for _ in range(stop_iters):
                result = algo.train()
                rt_train.report(result)
                if (stop_reward is not None
                        and result["episode_return_mean"] >= stop_reward):
                    break
        finally:
            algo.stop()

    return trainable
