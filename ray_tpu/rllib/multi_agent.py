"""Multi-agent episodes: per-agent policies over one shared environment.

Reference analogs: ``rllib/env/multi_agent_env.py`` (the env protocol:
dict-keyed obs/action/reward/termination per agent plus ``__all__``),
``rllib/env/multi_agent_env_runner.py`` (episode collection splitting
per-agent transitions to their mapped policies), and the multi-policy
learner group. TPU-first shape: each policy's fragment is a dense
[T, n_agents_of_policy] struct-of-arrays — agents that terminate early are
masked via dones (their tail steps carry zero reward), so every learner
update stays one static-shaped XLA program.

Env protocol (duck-typed, gymnasium-flavored):
    reset(seed=...) -> (obs_dict, info)
    step(action_dict) -> (obs_dict, rew_dict, term_dict, trunc_dict, info)
        where term_dict/trunc_dict carry per-agent flags + "__all__"
    possible_agents: list of agent ids (fixed)
    observation_space(agent) / action_space(agent) (or shared
    observation_space/action_space attributes)
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rllib import module as rl_module
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.learner import Learner


def _space_for(env, agent, name):
    attr = getattr(env, name)
    return attr(agent) if callable(attr) else attr


def module_config_for_agent(env, agent) -> rl_module.RLModuleConfig:
    import gymnasium as gym

    obs_space = _space_for(env, agent, "observation_space")
    act_space = _space_for(env, agent, "action_space")
    obs_dim = int(np.prod(obs_space.shape))
    if isinstance(act_space, gym.spaces.Discrete):
        return rl_module.RLModuleConfig(
            obs_dim=obs_dim, action_dim=int(act_space.n), discrete=True
        )
    return rl_module.RLModuleConfig(
        obs_dim=obs_dim, action_dim=int(np.prod(act_space.shape)),
        discrete=False,
    )


class MultiAgentEnvRunner:
    """Collects per-policy fragments from one multi-agent env.

    All agents step together; a per-agent done masks its remaining steps in
    the fragment (obs frozen, reward 0) until the episode ends for all.
    """

    def __init__(self, env_creator: Callable[[], Any], fragment_len: int,
                 policy_module_configs: Dict[str, dict],
                 agent_to_policy: Dict[str, str], seed: int = 0):
        import jax

        self.env = env_creator()
        self.fragment_len = fragment_len
        self.agents: List[str] = list(self.env.possible_agents)
        self.agent_to_policy = dict(agent_to_policy)
        self.policies = sorted(policy_module_configs)
        self.configs = {
            p: rl_module.RLModuleConfig(**c)
            for p, c in policy_module_configs.items()
        }
        # agents grouped per policy, in stable order: column layout of the
        # per-policy fragment arrays
        self.policy_agents = {
            p: [a for a in self.agents if self.agent_to_policy[a] == p]
            for p in self.policies
        }
        self.params: Dict[str, Any] = {}
        self.rng = jax.random.PRNGKey(seed)
        self._sample_fns = {
            p: jax.jit(
                lambda prm, obs, rng, c=self.configs[p]:
                rl_module.sample_action(prm, c, obs, rng)
            )
            for p in self.policies
        }
        self._value_fns = {
            p: jax.jit(
                lambda prm, obs, c=self.configs[p]:
                rl_module.forward_value(prm, c, obs)
            )
            for p in self.policies
        }
        self._seed = seed
        self._episode_seed = seed
        self._reset_episode()
        self._completed: List[tuple] = []
        self._total_steps = 0

    def _reset_episode(self):
        self._episode_seed += 1
        obs, _ = self.env.reset(seed=self._episode_seed)
        self.obs = {a: np.asarray(obs[a], np.float32).ravel()
                    for a in self.agents}
        self.alive = {a: True for a in self.agents}
        self._ep_return = 0.0
        self._ep_len = 0

    def set_weights(self, params: Dict[str, Any]):
        self.params = params

    def ping(self) -> bool:
        return True

    def sample(self) -> Dict[str, Dict[str, np.ndarray]]:
        """Returns {policy_id: fragment} with arrays [T, A_p, ...]."""
        import jax

        assert self.params, "set_weights before sample"
        T = self.fragment_len
        bufs: Dict[str, Dict[str, np.ndarray]] = {}
        for p in self.policies:
            A = len(self.policy_agents[p])
            cfg = self.configs[p]
            act_shape = (T, A) if cfg.discrete else (T, A, cfg.action_dim)
            bufs[p] = {
                "obs": np.zeros((T, A, cfg.obs_dim), np.float32),
                "actions": np.zeros(
                    act_shape, np.int32 if cfg.discrete else np.float32
                ),
                "rewards": np.zeros((T, A), np.float32),
                "dones": np.ones((T, A), np.float32),
                "truncateds": np.zeros((T, A), np.float32),
                "logp": np.zeros((T, A), np.float32),
                "values": np.zeros((T, A), np.float32),
            }
        for t in range(T):
            actions: Dict[str, Any] = {}
            for p in self.policies:
                agents = self.policy_agents[p]
                obs_mat = np.stack([self.obs[a] for a in agents])
                self.rng, k = jax.random.split(self.rng)
                act, logp, value = self._sample_fns[p](
                    self.params[p], obs_mat, k
                )
                act = np.asarray(act)
                b = bufs[p]
                b["obs"][t] = obs_mat
                b["actions"][t] = act
                b["logp"][t] = np.asarray(logp)
                b["values"][t] = np.asarray(value)
                for j, a in enumerate(agents):
                    if self.alive[a]:
                        actions[a] = (
                            int(act[j]) if self.configs[p].discrete
                            else act[j]
                        )
            nobs, rews, terms, truncs, _ = self.env.step(actions)
            self._ep_len += 1
            all_done = bool(terms.get("__all__")) or bool(
                truncs.get("__all__")
            )
            for p in self.policies:
                b = bufs[p]
                for j, a in enumerate(self.policy_agents[p]):
                    if not self.alive[a]:
                        continue  # masked: done stays 1, reward stays 0
                    r = float(rews.get(a, 0.0))
                    self._ep_return += r
                    b["rewards"][t, j] = r
                    done = bool(terms.get(a)) or bool(truncs.get(a)) \
                        or all_done
                    b["dones"][t, j] = float(done)
                    if truncs.get(a) and not terms.get(a):
                        b["truncateds"][t, j] = 1.0
                    if a in nobs:
                        self.obs[a] = np.asarray(
                            nobs[a], np.float32
                        ).ravel()
                    if done:
                        self.alive[a] = False
            if all_done or not any(self.alive.values()):
                self._completed.append((self._ep_return, self._ep_len))
                self._reset_episode()
        out = {}
        for p in self.policies:
            agents = self.policy_agents[p]
            obs_mat = np.stack([self.obs[a] for a in agents])
            boot = np.asarray(self._value_fns[p](self.params[p], obs_mat))
            # a freshly reset episode bootstraps its value; mid-episode
            # dead agents contribute 0 via their done mask anyway
            out[p] = {**bufs[p], "bootstrap_value": boot}
        self._total_steps += T * len(self.agents)
        return out

    def metrics(self) -> Dict[str, Any]:
        completed, self._completed = self._completed, []
        return {
            "num_episodes": len(completed),
            "episode_returns": [r for r, _ in completed],
            "episode_lengths": [l for _, l in completed],
            "total_steps": self._total_steps,
        }


class MultiAgentPPOConfig(AlgorithmConfig):
    """PPO over per-policy learners (reference:
    ``AlgorithmConfig.multi_agent(policies=..., policy_mapping_fn=...)``)."""

    algo_name = "ppo"

    def __init__(self):
        super().__init__()
        self.policies: Optional[List[str]] = None
        self.policy_mapping_fn: Callable[[str], str] = lambda agent: "default"
        self.training(
            lr=3e-4, clip_param=0.2, vf_coeff=0.5, entropy_coeff=0.01,
            num_sgd_epochs=4, minibatch_count=4, gae_lambda=0.95,
        )

    def multi_agent(self, *, policies: List[str],
                    policy_mapping_fn: Callable[[str], str]):
        self.policies = list(policies)
        self.policy_mapping_fn = policy_mapping_fn
        return self

    def build_algo(self) -> "MultiAgentPPO":
        return MultiAgentPPO(self)


class MultiAgentPPO(Algorithm):
    """One PPO learner per policy; runners split episodes per policy."""

    def __init__(self, config: MultiAgentPPOConfig):
        import ray_tpu

        self.config = config
        creator = config.get_env_creator()
        probe = creator()
        agents = list(probe.possible_agents)
        self.agent_to_policy = {
            a: config.policy_mapping_fn(a) for a in agents
        }
        if config.policies is None:
            config.policies = sorted(set(self.agent_to_policy.values()))
        missing = set(self.agent_to_policy.values()) - set(config.policies)
        if missing:
            raise ValueError(f"policy_mapping_fn produced unknown {missing}")
        self.module_configs = {}
        for p in config.policies:
            agent = next(
                a for a in agents if self.agent_to_policy[a] == p
            )
            self.module_configs[p] = module_config_for_agent(probe, agent)
        if hasattr(probe, "close"):
            probe.close()
        self.learners = {
            p: Learner("ppo", self.module_configs[p], config.hp,
                       seed=config.seed + i, mesh=config.mesh)
            for i, p in enumerate(sorted(config.policies))
        }
        cfg_dicts = {
            p: dict(c.__dict__) for p, c in self.module_configs.items()
        }
        self._make_runner = lambda idx: ray_tpu.remote(
            MultiAgentEnvRunner
        ).options(
            name=f"ma_runner_{idx}_{time.monotonic_ns()}", num_cpus=1
        ).remote(
            creator, config.rollout_fragment_length, cfg_dicts,
            self.agent_to_policy, config.seed + 1000 * idx,
        )
        self.runners = [
            self._make_runner(i) for i in range(config.num_env_runners)
        ]
        self.iteration = 0
        self._total_env_steps = 0
        self._last_step_count = 0
        self._recent_returns: List[float] = []
        self._sync_weights()

    # runner group (inline: per-policy weight dict) ------------------------

    def _sync_weights(self):
        import ray_tpu

        weights = {p: l.get_weights() for p, l in self.learners.items()}
        ray_tpu.get([r.set_weights.remote(weights) for r in self.runners])

    def _sample_all(self):
        import ray_tpu

        out = []
        dead = []
        for i, r in enumerate(self.runners):
            try:
                out.append(ray_tpu.get(r.sample.remote(), timeout=120))
            except Exception:
                dead.append(i)
        for i in dead:
            self.runners[i] = self._make_runner(i)
            try:
                weights = {
                    p: l.get_weights() for p, l in self.learners.items()
                }
                ray_tpu.get(
                    self.runners[i].set_weights.remote(weights), timeout=60
                )
            except Exception:
                pass
        return out

    def training_step(self) -> Dict[str, float]:
        fragments = self._sample_all()
        if not fragments:
            self._last_step_count = 0
            return {"num_healthy_runners": 0}
        metrics: Dict[str, float] = {}
        steps = 0
        for p, learner in self.learners.items():
            frags = [f[p] for f in fragments]
            batch = self._build_batch(frags)
            m = learner.update(batch)
            steps += batch["rewards"].shape[0] * batch["rewards"].shape[1]
            metrics.update({f"{p}/{k}": v for k, v in m.items()})
            metrics.setdefault("total_loss", 0.0)
            metrics["total_loss"] += m.get("total_loss", 0.0)
        self._total_env_steps += steps
        self._last_step_count = steps
        self._sync_weights()
        return metrics

    def _record_env_steps(self, batch):  # steps counted in training_step
        pass

    def metrics_runner_group(self):
        import ray_tpu

        out = []
        for r in self.runners:
            try:
                out.append(ray_tpu.get(r.metrics.remote(), timeout=30))
            except Exception:
                pass
        return out

    # Algorithm.train() calls self.runner_group.metrics(); provide a shim.
    @property
    def runner_group(self):
        algo = self

        class _Shim:
            def metrics(self):
                return algo.metrics_runner_group()

            def stop(self):
                import ray_tpu

                for r in algo.runners:
                    try:
                        ray_tpu.kill(r)
                    except Exception:
                        pass

        return _Shim()

    def get_policy_weights(self, policy_id: str):
        return self.learners[policy_id].get_weights()

    # per-policy checkpointing (the base save/restore assume one learner)

    def save(self, path: str) -> str:
        import os
        import pickle

        os.makedirs(path, exist_ok=True)
        state = {
            "learners": {p: l.state() for p, l in self.learners.items()},
            "iteration": self.iteration,
            "total_env_steps": self._total_env_steps,
            "module_configs": {
                p: dict(c.__dict__) for p, c in self.module_configs.items()
            },
            "agent_to_policy": self.agent_to_policy,
            "algo": "multi_agent_ppo",
        }
        with open(os.path.join(path, "algorithm_state.pkl"), "wb") as f:
            pickle.dump(state, f)
        return path

    def restore(self, path: str):
        import os
        import pickle

        with open(os.path.join(path, "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        for p, lstate in state["learners"].items():
            self.learners[p].restore(lstate)
        self.iteration = state["iteration"]
        self._total_env_steps = state["total_env_steps"]
        self._sync_weights()

    def stop(self):
        self.runner_group.stop()
