"""RLModule: policy/value networks in pure functional JAX.

Reference analog: ``rllib/core/rl_module/`` (RLModule abstraction; the
default PPO torch module is an MLP encoder with policy and value heads).
TPU-first choices: params are a plain pytree (same idiom as
``ray_tpu/models/gpt2.py``) so learner steps jit/shard them directly; action
distributions are computed inside jit (categorical for discrete spaces,
diagonal gaussian for box spaces).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class RLModuleConfig:
    obs_dim: int
    action_dim: int
    discrete: bool = True
    hidden: Sequence[int] = (64, 64)
    dtype: Any = jnp.float32
    # Initial log-stddev for gaussian policies.
    init_logstd: float = 0.0
    # "categorical" (PG methods) | "epsilon_greedy" (value methods: the pi
    # head outputs Q-values; exploration epsilon rides params["epsilon"] so
    # decay flows to runners through weight sync) | "squashed_gaussian"
    # (SAC: the pi head outputs [mean, logstd] and actions are
    # tanh-squashed samples).
    exploration: str = "categorical"


def _init_mlp(rng, sizes, dtype):
    layers = []
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        rng, k = jax.random.split(rng)
        scale = np.sqrt(2.0 / fan_in)
        # final layer: small init stabilizes early policy/value outputs
        if i == len(sizes) - 2:
            scale = 0.01
        layers.append({
            "w": (jax.random.normal(k, (fan_in, fan_out)) * scale).astype(dtype),
            "b": jnp.zeros((fan_out,), dtype),
        })
    return layers


def init_params(config: RLModuleConfig, rng) -> Dict[str, Any]:
    k_pi, k_vf = jax.random.split(rng)
    sizes = [config.obs_dim, *config.hidden]
    # squashed_gaussian: state-dependent logstd rides the pi head
    out_dim = (
        2 * config.action_dim
        if config.exploration == "squashed_gaussian"
        else config.action_dim
    )
    params = {
        "pi": _init_mlp(k_pi, sizes + [out_dim], config.dtype),
        "vf": _init_mlp(k_vf, sizes + [1], config.dtype),
    }
    if not config.discrete and config.exploration != "squashed_gaussian":
        params["logstd"] = jnp.full(
            (config.action_dim,), config.init_logstd, config.dtype
        )
    return params


def _mlp(layers, x):
    for i, lyr in enumerate(layers):
        x = x @ lyr["w"] + lyr["b"]
        if i < len(layers) - 1:
            x = jnp.tanh(x)
    return x


def forward_policy(params, config: RLModuleConfig, obs):
    """Returns distribution inputs: logits (discrete) or mean (box)."""
    return _mlp(params["pi"], obs)


def forward_value(params, config: RLModuleConfig, obs):
    if config.exploration == "epsilon_greedy":
        # value-based module: the state value is max_a Q — the vf head is
        # untrained (TD only updates pi/Q), so using it (e.g. for the
        # runner's truncation bootstrap) would silently bias targets.
        return jnp.max(forward_policy(params, config, obs), axis=-1)
    return _mlp(params["vf"], obs)[..., 0]


LOGSTD_MIN, LOGSTD_MAX = -20.0, 2.0


def squashed_gaussian_dist(params, config: RLModuleConfig, obs):
    """(mean, logstd) of the pre-tanh gaussian (SAC policy head)."""
    out = forward_policy(params, config, obs)
    mean, logstd = jnp.split(out, 2, axis=-1)
    return mean, jnp.clip(logstd, LOGSTD_MIN, LOGSTD_MAX)


def squashed_sample_logp(mean, logstd, rng):
    """Reparameterized tanh-squashed sample and its log-prob."""
    std = jnp.exp(logstd)
    pre = mean + std * jax.random.normal(rng, mean.shape)
    action = jnp.tanh(pre)
    logp = _gaussian_logp(pre, mean, logstd)
    # tanh change of variables (numerically stable form)
    logp = logp - jnp.sum(
        2.0 * (jnp.log(2.0) - pre - jax.nn.softplus(-2.0 * pre)), axis=-1
    )
    return action, logp


def sample_action(params, config: RLModuleConfig, obs, rng):
    """(action, logp, value) for rollout collection — one fused jit."""
    if config.exploration == "squashed_gaussian":
        mean, logstd = squashed_gaussian_dist(params, config, obs)
        action, logp = squashed_sample_logp(mean, logstd, rng)
        # off-policy (replay) training: the runner-side value is unused;
        # the vf head is untrained so returning it would bias bootstraps
        value = jnp.zeros(logp.shape, mean.dtype)
        return action, logp, value
    out = forward_policy(params, config, obs)
    value = forward_value(params, config, obs)
    if config.exploration == "epsilon_greedy":
        # out = Q-values; epsilon-greedy with epsilon carried in params
        k_eps, k_rand = jax.random.split(rng)
        eps = params.get("epsilon", jnp.float32(0.0))
        greedy = jnp.argmax(out, axis=-1)
        random_a = jax.random.randint(
            k_rand, greedy.shape, 0, config.action_dim
        )
        explore = jax.random.uniform(k_eps, greedy.shape) < eps
        action = jnp.where(explore, random_a, greedy)
        logp = jnp.zeros(action.shape, out.dtype)  # off-policy: unused
        return action, logp, value  # value = max Q via forward_value
    if config.discrete:
        logits = jax.nn.log_softmax(out)
        action = jax.random.categorical(rng, out)
        logp = jnp.take_along_axis(logits, action[..., None], -1)[..., 0]
    else:
        std = jnp.exp(params["logstd"])
        noise = jax.random.normal(rng, out.shape)
        action = out + std * noise
        logp = _gaussian_logp(action, out, params["logstd"])
    return action, logp, value


def _gaussian_logp(x, mean, logstd):
    var = jnp.exp(2 * logstd)
    return jnp.sum(
        -0.5 * ((x - mean) ** 2 / var + 2 * logstd + jnp.log(2 * jnp.pi)),
        axis=-1,
    )


def logp_entropy_value(params, config: RLModuleConfig, obs, actions):
    """(logp, entropy, value) of given actions — the learner-side forward."""
    out = forward_policy(params, config, obs)
    value = forward_value(params, config, obs)
    if config.discrete:
        logits = jax.nn.log_softmax(out)
        logp = jnp.take_along_axis(
            logits, actions.astype(jnp.int32)[..., None], -1
        )[..., 0]
        probs = jnp.exp(logits)
        entropy = -jnp.sum(probs * logits, axis=-1)
    else:
        logp = _gaussian_logp(actions, out, params["logstd"])
        entropy = jnp.sum(params["logstd"] + 0.5 * jnp.log(2 * jnp.pi * jnp.e))
        entropy = jnp.broadcast_to(entropy, logp.shape)
    return logp, entropy, value


def module_config_for_env(env) -> RLModuleConfig:
    """Infer obs/action dims from a gymnasium env."""
    import gymnasium as gym

    obs_dim = int(np.prod(env.observation_space.shape))
    if isinstance(env.action_space, gym.spaces.Discrete):
        return RLModuleConfig(obs_dim=obs_dim, action_dim=int(env.action_space.n),
                              discrete=True)
    return RLModuleConfig(
        obs_dim=obs_dim, action_dim=int(np.prod(env.action_space.shape)),
        discrete=False,
    )
