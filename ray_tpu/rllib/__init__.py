"""RLlib-equivalent: RL training on the TPU-native stack.

Reference analog: the ``rllib/`` tree (new API stack: EnvRunnerGroup +
RLModule + Learner/LearnerGroup + Algorithm/AlgorithmConfig).
"""
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig, make_trainable
from ray_tpu.rllib.algorithms import (
    BC,
    BCConfig,
    CQL,
    CQLConfig,
    IQL,
    IQLConfig,
    DQN,
    DQNConfig,
    IMPALA,
    IMPALAConfig,
    MARWIL,
    MARWILConfig,
    PPO,
    PPOConfig,
    SAC,
    SACConfig,
)
from ray_tpu.rllib.env_runner import EnvRunnerGroup, SingleAgentEnvRunner
from ray_tpu.rllib.learner import Learner, LearnerHyperparams

__all__ = [
    "CQL",
    "CQLConfig",
    "IQL",
    "IQLConfig",
    "Algorithm", "AlgorithmConfig", "make_trainable",
    "PPO", "PPOConfig", "IMPALA", "IMPALAConfig", "DQN", "DQNConfig",
    "SAC", "SACConfig", "MARWIL", "MARWILConfig", "BC", "BCConfig",
    "EnvRunnerGroup", "SingleAgentEnvRunner",
    "Learner", "LearnerHyperparams",
]
