"""RLlib-equivalent: RL training on the TPU-native stack.

Reference analog: the ``rllib/`` tree (new API stack: EnvRunnerGroup +
RLModule + Learner/LearnerGroup + Algorithm/AlgorithmConfig).
"""
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig, make_trainable
from ray_tpu.rllib.algorithms import (
    BC,
    BCConfig,
    CQL,
    CQLConfig,
    IQL,
    IQLConfig,
    DQN,
    DQNConfig,
    DreamerV3,
    DreamerV3Config,
    IMPALA,
    IMPALAConfig,
    MARWIL,
    MARWILConfig,
    PPO,
    PPOConfig,
    SAC,
    SACConfig,
    TQC,
    TQCConfig,
)
from ray_tpu.rllib.connectors import (
    ClipActions,
    ClipObs,
    ConnectorPipelineV2,
    ConnectorV2,
    FlattenObs,
    FrameStack,
    MeanStdFilter,
    RescaleActions,
)
from ray_tpu.rllib.env_runner import EnvRunnerGroup, SingleAgentEnvRunner
from ray_tpu.rllib.learner import Learner, LearnerHyperparams

__all__ = [
    "CQL",
    "CQLConfig",
    "IQL",
    "IQLConfig",
    "Algorithm", "AlgorithmConfig", "make_trainable",
    "PPO", "PPOConfig", "IMPALA", "IMPALAConfig", "DQN", "DQNConfig",
    "SAC", "SACConfig", "TQC", "TQCConfig",
    "DreamerV3", "DreamerV3Config",
    "MARWIL", "MARWILConfig", "BC", "BCConfig",
    "ConnectorV2", "ConnectorPipelineV2", "MeanStdFilter", "FlattenObs",
    "ClipObs", "FrameStack", "ClipActions", "RescaleActions",
    "EnvRunnerGroup", "SingleAgentEnvRunner",
    "Learner", "LearnerHyperparams",
]
