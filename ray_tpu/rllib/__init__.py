"""RLlib-equivalent: RL training on the TPU-native stack.

Reference analog: the ``rllib/`` tree (new API stack: EnvRunnerGroup +
RLModule + Learner/LearnerGroup + Algorithm/AlgorithmConfig).
"""
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig, make_trainable
from ray_tpu.rllib.algorithms import (
    DQN,
    DQNConfig,
    IMPALA,
    IMPALAConfig,
    PPO,
    PPOConfig,
)
from ray_tpu.rllib.env_runner import EnvRunnerGroup, SingleAgentEnvRunner
from ray_tpu.rllib.learner import Learner, LearnerHyperparams

__all__ = [
    "Algorithm", "AlgorithmConfig", "make_trainable",
    "PPO", "PPOConfig", "IMPALA", "IMPALAConfig", "DQN", "DQNConfig",
    "EnvRunnerGroup", "SingleAgentEnvRunner",
    "Learner", "LearnerHyperparams",
]
