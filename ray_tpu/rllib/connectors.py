"""ConnectorV2: composable env<->module transform pipelines.

Reference analog: ``rllib/connectors/`` (ConnectorV2 + ConnectorPipelineV2 —
the new-API-stack abstraction that moves observation/action preprocessing
out of env and module code into explicit, stateful, checkpointable
pipelines; ``rllib/connectors/connector_pipeline_v2.py``).

Three pipeline slots, mirroring the reference:

- **env-to-module**: raw env observations -> module input (normalize,
  clip, stack). Runs on every env runner before policy inference AND on
  the learner batch before the update (same transform both places, so the
  module always sees one distribution).
- **module-to-env**: module action output -> env action (clip/rescale).
- **learner**: training-batch-only transforms.

Stateful connectors (e.g. ``MeanStdFilter``) expose ``get_state`` /
``set_state`` / ``merge_states``; the runner group pulls per-runner states
each iteration, merges them (count-weighted moment merge), and broadcasts
the result — the reference's ``merge_env_runner_states`` flow — so every
runner and the learner normalize with the same statistics.

TPU note: connectors run host-side on numpy fragments (runner loops are
CPU-bound env stepping anyway); the jitted policy/learner programs stay
pure and static-shaped.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np


class ConnectorV2:
    """One transform stage. Subclasses override ``__call__``.

    module-to-env stages that map policy actions into env action bounds
    should declare it: ``rescales_actions = True`` for a [-1,1]->bounds
    rescale, ``clips_actions = True`` for a clip. The env runner keeps its
    BUILT-IN rescale/clip unless the pipeline declares one — a pipeline
    that only e.g. logs actions must not silently disable the
    squashed-gaussian rescale, and a pipeline with its own rescale must
    not get a second one stacked on top."""

    rescales_actions = False
    clips_actions = False

    def __call__(self, batch: Dict[str, np.ndarray], **kw) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    # state sync (stateless connectors keep the defaults) ------------------

    def get_state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        pass

    @staticmethod
    def merge_states(states: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
        return states[0] if states else {}


class ConnectorPipelineV2(ConnectorV2):
    """Ordered list of connectors applied in sequence."""

    def __init__(self, connectors: Optional[List[ConnectorV2]] = None):
        self.connectors = list(connectors or [])

    def append(self, connector: ConnectorV2) -> "ConnectorPipelineV2":
        self.connectors.append(connector)
        return self

    def prepend(self, connector: ConnectorV2) -> "ConnectorPipelineV2":
        self.connectors.insert(0, connector)
        return self

    def __call__(self, batch, **kw):
        for c in self.connectors:
            batch = c(batch, **kw)
        return batch

    def __len__(self):
        return len(self.connectors)

    def get_state(self):
        return {i: c.get_state() for i, c in enumerate(self.connectors)}

    def set_state(self, state):
        for i, c in enumerate(self.connectors):
            if i in state or str(i) in state:
                c.set_state(state.get(i, state.get(str(i))))

    def merge_states_from(
        self, states: Sequence[Dict[str, Any]]
    ) -> Dict[Any, Dict[str, Any]]:
        """Merge per-runner pipeline states index-by-index, apply the
        result to this pipeline (via set_state), and return it."""
        merged: Dict[Any, Dict[str, Any]] = {}
        for i, c in enumerate(self.connectors):
            per_conn = [s[i] for s in states if i in s and s[i]]
            if per_conn:
                merged[i] = type(c).merge_states(per_conn)
                c.set_state(merged[i])
        return merged


# ---------------------------------------------------------------- built-ins


class FlattenObs(ConnectorV2):
    """Flatten trailing obs dims to 1-D vectors (batch axis preserved)."""

    def __call__(self, batch, **kw):
        obs = batch["obs"]
        if obs.ndim > 2:
            batch = dict(batch)
            batch["obs"] = obs.reshape(obs.shape[0], -1)
        return batch


class ClipObs(ConnectorV2):
    def __init__(self, low: float = -10.0, high: float = 10.0):
        self.low, self.high = low, high

    def __call__(self, batch, **kw):
        batch = dict(batch)
        batch["obs"] = np.clip(batch["obs"], self.low, self.high)
        return batch


class MeanStdFilter(ConnectorV2):
    """Running-moment observation normalizer (reference:
    ``rllib/connectors/env_to_module/mean_std_filter.py``).

    Tracks count/mean/M2 via Welford accumulation; ``merge_states`` uses
    the parallel-variance (Chan) merge so per-runner statistics combine
    exactly, independent of runner count or fragment interleaving.

    Sync contract: each instance accumulates ONLY its own observations
    (``get_state`` reports those), while normalization prefers the merged
    cluster statistics received via ``set_state``. Keeping the two
    separate means repeated merge→broadcast rounds never double-count a
    runner's samples.
    """

    def __init__(self, shape: Optional[tuple] = None, clip: float = 10.0,
                 update: bool = True):
        self.clip = clip
        self.update = update
        self.count = 0.0
        self.mean = np.zeros(shape, np.float64) if shape else None
        self.m2 = np.zeros(shape, np.float64) if shape else None
        self._applied: Optional[Dict[str, Any]] = None  # broadcast stats

    def _ensure(self, dim):
        if self.mean is None:
            self.mean = np.zeros(dim, np.float64)
            self.m2 = np.zeros(dim, np.float64)

    def __call__(self, batch, **kw):
        obs = np.asarray(batch["obs"], np.float64)
        flat = obs.reshape(-1, obs.shape[-1])
        self._ensure(flat.shape[-1])
        if self.update and kw.get("training", True):
            n = flat.shape[0]
            b_mean = flat.mean(0)
            b_m2 = ((flat - b_mean) ** 2).sum(0)
            delta = b_mean - self.mean
            tot = self.count + n
            self.mean = self.mean + delta * (n / tot)
            self.m2 = self.m2 + b_m2 + delta ** 2 * (self.count * n / tot)
            self.count = tot
        mean, std = self._norm_stats()
        out = (obs - mean) / std
        batch = dict(batch)
        batch["obs"] = np.clip(out, -self.clip, self.clip).astype(np.float32)
        return batch

    def _norm_stats(self):
        """(mean, std) used for normalization: the merged cluster stats
        when a broadcast arrived, else this instance's own."""
        a = self._applied
        if a is not None and a["count"] >= 2:
            return a["mean"], np.sqrt(
                np.maximum(a["m2"] / a["count"], 1e-8)
            )
        return self.mean, self.std

    @property
    def std(self) -> np.ndarray:
        if self.count < 2:
            return np.ones_like(self.mean) if self.mean is not None else 1.0
        return np.sqrt(np.maximum(self.m2 / self.count, 1e-8))

    def get_state(self):
        if self.mean is None:
            return {}
        return {
            "count": float(self.count),
            "mean": self.mean.copy(),
            "m2": self.m2.copy(),
        }

    def set_state(self, state):
        if not state:
            return
        self._applied = {
            "count": float(state["count"]),
            "mean": np.asarray(state["mean"], np.float64).copy(),
            "m2": np.asarray(state["m2"], np.float64).copy(),
        }

    @staticmethod
    def merge_states(states):
        states = [s for s in states if s]
        if not states:
            return {}
        count = states[0]["count"]
        mean = np.asarray(states[0]["mean"], np.float64).copy()
        m2 = np.asarray(states[0]["m2"], np.float64).copy()
        for s in states[1:]:
            n2, mean2 = s["count"], np.asarray(s["mean"], np.float64)
            delta = mean2 - mean
            tot = count + n2
            mean = mean + delta * (n2 / tot)
            m2 = m2 + np.asarray(s["m2"], np.float64) + (
                delta ** 2 * (count * n2 / tot)
            )
            count = tot
        return {"count": count, "mean": mean, "m2": m2}


class FrameStack(ConnectorV2):
    """Stack the last k observations per env along the feature axis.

    Operates on [N, obs_dim] inference batches; keeps a per-env deque of
    previous frames. ``dones`` (when provided via kw) reset a column's
    history so frames never bleed across episode boundaries.
    """

    def __init__(self, k: int = 4):
        self.k = k
        self._hist: Optional[np.ndarray] = None  # [N, k, obs_dim]

    def __call__(self, batch, dones: Optional[np.ndarray] = None,
                 training: bool = True, **kw):
        obs = np.asarray(batch["obs"], np.float32)
        n, d = obs.shape
        if not training:
            # One-off probe (e.g. a truncation value read): answer without
            # touching per-env history — treat the frame as a fresh stack.
            batch = dict(batch)
            batch["obs"] = np.tile(obs, (1, self.k))
            return batch
        if self._hist is None or self._hist.shape[0] != n:
            self._hist = np.repeat(obs[:, None, :], self.k, axis=1)
        else:
            self._hist = np.concatenate(
                [self._hist[:, 1:], obs[:, None, :]], axis=1
            )
        if dones is not None:
            for i in np.nonzero(dones)[0]:
                self._hist[i] = obs[i][None, :]
        batch = dict(batch)
        batch["obs"] = self._hist.reshape(n, self.k * d)
        return batch


class ClipActions(ConnectorV2):
    """module-to-env: clip actions into the env's Box bounds."""

    clips_actions = True

    def __init__(self, low, high):
        self.low = np.asarray(low, np.float32)
        self.high = np.asarray(high, np.float32)

    def __call__(self, batch, **kw):
        batch = dict(batch)
        batch["actions"] = np.clip(batch["actions"], self.low, self.high)
        return batch


class RescaleActions(ConnectorV2):
    """module-to-env: map [-1, 1] policy actions to the env's Box bounds
    (what squashed-gaussian policies emit)."""

    rescales_actions = True

    def __init__(self, low, high):
        self.low = np.asarray(low, np.float32)
        self.high = np.asarray(high, np.float32)

    def __call__(self, batch, **kw):
        batch = dict(batch)
        a = np.asarray(batch["actions"], np.float32)
        batch["actions"] = self.low + (a + 1.0) * 0.5 * (self.high - self.low)
        return batch
