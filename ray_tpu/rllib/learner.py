"""Learners: SPMD JAX gradient updates on rollout batches.

Reference analog: ``rllib/core/learner/learner.py`` + ``learner_group.py:100``
(remote learner actors, DDP gradient sync). TPU-first difference: ONE learner
process drives an SPMD step over a device mesh — gradients sync through XLA
collectives from sharding annotations (scaling-book recipe), not through a
torch-DDP-style host loop. A multi-host LearnerGroup shape is kept (list of
learner actors, weight averaging via the collective layer) for DCN-spanning
setups.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.rllib import module as rl_module


@dataclass(frozen=True)
class LearnerHyperparams:
    lr: float = 3e-4
    grad_clip: float = 0.5
    gamma: float = 0.99
    # PPO
    gae_lambda: float = 0.95
    clip_param: float = 0.2
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    num_sgd_epochs: int = 4
    minibatch_count: int = 4
    # IMPALA / V-trace
    vtrace_rho_clip: float = 1.0
    vtrace_c_clip: float = 1.0


def compute_gae(rewards, dones, values, bootstrap_value, gamma, lam):
    """Generalized advantage estimation over [T, N] fragments (jit-safe).

    dones cut the recursion at episode ends; the bootstrap value closes the
    final partial episode of each env.
    """
    T = rewards.shape[0]
    next_values = jnp.concatenate([values[1:], bootstrap_value[None]], axis=0)
    deltas = rewards + gamma * next_values * (1.0 - dones) - values

    def scan_fn(carry, t):
        adv = deltas[t] + gamma * lam * (1.0 - dones[t]) * carry
        return adv, adv

    _, advs = jax.lax.scan(scan_fn, jnp.zeros_like(bootstrap_value),
                           jnp.arange(T - 1, -1, -1))
    advs = advs[::-1]
    return advs, advs + values


def make_ppo_update(config: rl_module.RLModuleConfig,
                    hp: LearnerHyperparams,
                    optimizer: optax.GradientTransformation,
                    mesh: Optional[Mesh] = None):
    """Jitted PPO update: GAE + clipped surrogate, minibatched SGD epochs
    folded into ONE jit via lax.scan over shuffled minibatch index sets (no
    per-minibatch dispatch from Python).

    With a mesh, batch inputs are sharded over the ``data`` axis and params
    replicated — XLA inserts the gradient psum (DP over ICI).
    """

    def loss_fn(params, obs, actions, logp_old, advs, targets):
        logp, entropy, value = rl_module.logp_entropy_value(
            params, config, obs, actions
        )
        ratio = jnp.exp(logp - logp_old)
        pg1 = ratio * advs
        pg2 = jnp.clip(ratio, 1 - hp.clip_param, 1 + hp.clip_param) * advs
        pg_loss = -jnp.mean(jnp.minimum(pg1, pg2))
        vf_loss = 0.5 * jnp.mean((value - targets) ** 2)
        ent = jnp.mean(entropy)
        total = pg_loss + hp.vf_coeff * vf_loss - hp.entropy_coeff * ent
        kl = jnp.mean(logp_old - logp)
        return total, {
            "policy_loss": pg_loss, "vf_loss": vf_loss, "entropy": ent,
            "kl": kl,
        }

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def update(params, opt_state, batch, rng):
        obs = batch["obs"]
        T, N = obs.shape[:2]
        advs, targets = compute_gae(
            batch["rewards"], batch["dones"], batch["values"],
            batch["bootstrap_value"], hp.gamma, hp.gae_lambda,
        )
        flat = lambda x: x.reshape((T * N,) + x.shape[2:])
        obs_f, act_f = flat(obs), flat(batch["actions"])
        logp_f, advs_f, tgt_f = flat(batch["logp"]), flat(advs), flat(targets)
        advs_f = (advs_f - advs_f.mean()) / (advs_f.std() + 1e-8)

        B = T * N
        mb = B // hp.minibatch_count

        def epoch_step(carry, key):
            params, opt_state = carry
            perm = jax.random.permutation(key, B)

            def mb_step(carry, idx):
                params, opt_state = carry
                sel = jax.lax.dynamic_slice_in_dim(perm, idx * mb, mb)
                (l, aux), grads = grad_fn(
                    params, obs_f[sel], act_f[sel], logp_f[sel],
                    advs_f[sel], tgt_f[sel],
                )
                updates, opt_state = optimizer.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), (l, aux)

            (params, opt_state), (ls, auxs) = jax.lax.scan(
                mb_step, (params, opt_state), jnp.arange(hp.minibatch_count)
            )
            return (params, opt_state), (ls, auxs)

        keys = jax.random.split(rng, hp.num_sgd_epochs)
        (params, opt_state), (ls, auxs) = jax.lax.scan(
            epoch_step, (params, opt_state), keys
        )
        metrics = {
            "total_loss": ls.mean(),
            **{k: v.mean() for k, v in auxs.items()},
        }
        return params, opt_state, metrics

    if mesh is not None:
        batch_sharding = {
            "obs": NamedSharding(mesh, P(None, "data")),
            "actions": NamedSharding(mesh, P(None, "data")),
            "rewards": NamedSharding(mesh, P(None, "data")),
            "dones": NamedSharding(mesh, P(None, "data")),
            "logp": NamedSharding(mesh, P(None, "data")),
            "values": NamedSharding(mesh, P(None, "data")),
            "bootstrap_value": NamedSharding(mesh, P("data")),
        }
        repl = NamedSharding(mesh, P())
        return jax.jit(
            update,
            in_shardings=(repl, repl, batch_sharding, repl),
            out_shardings=(repl, repl, repl),
        )
    return jax.jit(update)


def vtrace(logp_target, logp_behavior, rewards, dones, values,
           bootstrap_value, gamma, rho_clip, c_clip):
    """V-trace targets/advantages (IMPALA off-policy correction) over [T, N].

    Follows the published recursion: vs = V(xs) + sum_t (gamma c_prod) delta;
    implemented as a reverse lax.scan.
    """
    rhos = jnp.exp(logp_target - logp_behavior)
    clipped_rhos = jnp.minimum(rhos, rho_clip)
    cs = jnp.minimum(rhos, c_clip)
    next_values = jnp.concatenate([values[1:], bootstrap_value[None]], axis=0)
    discounts = gamma * (1.0 - dones)
    deltas = clipped_rhos * (rewards + discounts * next_values - values)

    def scan_fn(acc, t):
        acc = deltas[t] + discounts[t] * cs[t] * acc
        return acc, acc

    T = rewards.shape[0]
    _, dv = jax.lax.scan(scan_fn, jnp.zeros_like(bootstrap_value),
                         jnp.arange(T - 1, -1, -1))
    dv = dv[::-1]
    vs = values + dv
    next_vs = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    pg_advs = clipped_rhos * (rewards + discounts * next_vs - values)
    return vs, pg_advs


def make_impala_update(config: rl_module.RLModuleConfig,
                       hp: LearnerHyperparams,
                       optimizer: optax.GradientTransformation,
                       mesh: Optional[Mesh] = None):
    """Jitted IMPALA update: V-trace corrected policy gradient + value MSE.
    One gradient step per incoming fragment batch (the actor-learner
    decoupling lives in Algorithm, which keeps sampling while learning)."""

    def loss_fn(params, batch):
        obs, actions = batch["obs"], batch["actions"]
        T, N = obs.shape[:2]
        logp, entropy, value = rl_module.logp_entropy_value(
            params, config, obs.reshape((T * N,) + obs.shape[2:]),
            actions.reshape((T * N,) + actions.shape[2:]),
        )
        logp = logp.reshape(T, N)
        value = value.reshape(T, N)
        entropy = entropy.reshape(T, N)
        vs, pg_advs = vtrace(
            jax.lax.stop_gradient(logp), batch["logp"], batch["rewards"],
            batch["dones"], jax.lax.stop_gradient(value),
            batch["bootstrap_value"], hp.gamma, hp.vtrace_rho_clip,
            hp.vtrace_c_clip,
        )
        pg_loss = -jnp.mean(jax.lax.stop_gradient(pg_advs) * logp)
        vf_loss = 0.5 * jnp.mean((value - jax.lax.stop_gradient(vs)) ** 2)
        ent = jnp.mean(entropy)
        total = pg_loss + hp.vf_coeff * vf_loss - hp.entropy_coeff * ent
        return total, {
            "policy_loss": pg_loss, "vf_loss": vf_loss, "entropy": ent,
        }

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def update(params, opt_state, batch, rng):
        (l, aux), grads = grad_fn(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {"total_loss": l, **aux}

    if mesh is not None:
        sh = lambda spec: NamedSharding(mesh, spec)
        batch_sharding = {
            "obs": sh(P(None, "data")), "actions": sh(P(None, "data")),
            "rewards": sh(P(None, "data")), "dones": sh(P(None, "data")),
            "logp": sh(P(None, "data")), "values": sh(P(None, "data")),
            "bootstrap_value": sh(P("data")),
        }
        repl = sh(P())
        return jax.jit(
            update,
            in_shardings=(repl, repl, batch_sharding, repl),
            out_shardings=(repl, repl, repl),
        )
    return jax.jit(update)


def make_appo_update(config: rl_module.RLModuleConfig,
                     hp: LearnerHyperparams,
                     optimizer: optax.GradientTransformation,
                     mesh: Optional[Mesh] = None):
    """Jitted APPO update (reference: ``rllib/algorithms/appo/appo.py`` —
    asynchronous PPO): IMPALA's actor-learner decoupling with V-trace
    off-policy correction, but the policy loss is PPO's clipped surrogate
    (ratio vs the BEHAVIOR policy) on the V-trace advantages instead of the
    plain importance-weighted gradient — stale fragments update stably
    without the synchronous on-policy barrier."""

    def loss_fn(params, batch):
        obs, actions = batch["obs"], batch["actions"]
        T, N = obs.shape[:2]
        logp, entropy, value = rl_module.logp_entropy_value(
            params, config, obs.reshape((T * N,) + obs.shape[2:]),
            actions.reshape((T * N,) + actions.shape[2:]),
        )
        logp = logp.reshape(T, N)
        value = value.reshape(T, N)
        entropy = entropy.reshape(T, N)
        vs, pg_advs = vtrace(
            jax.lax.stop_gradient(logp), batch["logp"], batch["rewards"],
            batch["dones"], jax.lax.stop_gradient(value),
            batch["bootstrap_value"], hp.gamma, hp.vtrace_rho_clip,
            hp.vtrace_c_clip,
        )
        advs = jax.lax.stop_gradient(pg_advs)
        advs = (advs - advs.mean()) / (advs.std() + 1e-8)
        ratio = jnp.exp(logp - batch["logp"])
        pg1 = ratio * advs
        pg2 = jnp.clip(ratio, 1 - hp.clip_param, 1 + hp.clip_param) * advs
        pg_loss = -jnp.mean(jnp.minimum(pg1, pg2))
        vf_loss = 0.5 * jnp.mean((value - jax.lax.stop_gradient(vs)) ** 2)
        ent = jnp.mean(entropy)
        kl = jnp.mean(batch["logp"] - logp)
        total = pg_loss + hp.vf_coeff * vf_loss - hp.entropy_coeff * ent
        return total, {
            "policy_loss": pg_loss, "vf_loss": vf_loss, "entropy": ent,
            "kl": kl,
        }

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def update(params, opt_state, batch, rng):
        (l, aux), grads = grad_fn(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {"total_loss": l, **aux}

    if mesh is not None:
        sh = lambda spec: NamedSharding(mesh, spec)
        batch_sharding = {
            "obs": sh(P(None, "data")), "actions": sh(P(None, "data")),
            "rewards": sh(P(None, "data")), "dones": sh(P(None, "data")),
            "logp": sh(P(None, "data")), "values": sh(P(None, "data")),
            "bootstrap_value": sh(P("data")),
        }
        repl = sh(P())
        return jax.jit(
            update,
            in_shardings=(repl, repl, batch_sharding, repl),
            out_shardings=(repl, repl, repl),
        )
    return jax.jit(update)


class Learner:
    """Owns params + optimizer state and applies jitted updates.

    Runs in the Algorithm process (single-controller SPMD over the local
    mesh). For multi-host DCN setups, wrap in actors and average weights
    through ``ray_tpu.util.collective`` — the group shape matches the
    reference's LearnerGroup.
    """

    def __init__(self, algo: str, module_config: rl_module.RLModuleConfig,
                 hp: LearnerHyperparams, seed: int = 0,
                 mesh: Optional[Mesh] = None):
        self.config = module_config
        self.hp = hp
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(hp.grad_clip),
            optax.adam(hp.lr),
        )
        self.rng = jax.random.PRNGKey(seed)
        self.rng, k = jax.random.split(self.rng)
        self.params = rl_module.init_params(module_config, k)
        self.opt_state = self.optimizer.init(self.params)
        make = {
            "ppo": make_ppo_update,
            "appo": make_appo_update,
        }.get(algo, make_impala_update)
        self._update = make(module_config, hp, self.optimizer, mesh)
        self.steps = 0

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        batch = {k: jnp.asarray(v) for k, v in batch.items()}
        self.rng, k = jax.random.split(self.rng)
        self.params, self.opt_state, metrics = self._update(
            self.params, self.opt_state, batch, k
        )
        self.steps += 1
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, params):
        self.params = jax.tree.map(jnp.asarray, params)

    def state(self) -> dict:
        return {
            "params": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
            "steps": self.steps,
        }

    def restore(self, state: dict):
        self.params = jax.tree.map(jnp.asarray, state["params"])
        self.opt_state = jax.tree.map(
            lambda x: jnp.asarray(x) if isinstance(x, (np.ndarray, jnp.ndarray)) else x,
            state["opt_state"],
        )
        self.steps = state["steps"]
