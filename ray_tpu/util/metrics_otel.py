"""OpenTelemetry export of the framework's metric registry.

Reference analog: ``src/ray/observability/open_telemetry_metric_recorder.cc``
— the reference records its C++ stats through an OTel recorder that exports
to the per-node metrics agent. Here the (Python) registry in
``ray_tpu.util.metrics`` gains an OTel bridge: observable instruments whose
callbacks read live registry snapshots, exported periodically by any
configured ``MetricExporter`` (OTLP, console, or in-memory for tests).

Import-guarded: ``opentelemetry`` is optional; everything raises a clear
ImportError naming the dependency when it is absent. Prometheus export
(``render_prometheus`` → dashboard ``/metrics``) is independent and remains
the default pipeline.
"""
from __future__ import annotations

from typing import Optional


def _require_otel():
    try:
        from opentelemetry.sdk.metrics import MeterProvider  # noqa: F401

        return True
    except ImportError as e:
        raise ImportError(
            "OTel metric export needs the 'opentelemetry-sdk' package "
            "(pip install opentelemetry-sdk); the Prometheus pipeline "
            "(dashboard /metrics) works without it."
        ) from e


class OtelMetricsBridge:
    """Bridges the process-local metric registry into an OTel
    MeterProvider via observable instruments.

    Counters → ObservableCounter (cumulative monotonic sums);
    Gauges → ObservableGauge; Histograms → per-series ``_sum``/``_count``
    observable counters plus cumulative ``_bucket`` counters (OTel has no
    observable histogram instrument — same flattening Prometheus uses).
    """

    def __init__(self, exporter=None, interval_ms: int = 5_000,
                 meter_name: str = "ray_tpu"):
        _require_otel()
        from opentelemetry.sdk.metrics import MeterProvider
        from opentelemetry.sdk.metrics.export import (
            ConsoleMetricExporter,
            PeriodicExportingMetricReader,
        )

        self._exporter = exporter or ConsoleMetricExporter()
        self._reader = PeriodicExportingMetricReader(
            self._exporter, export_interval_millis=interval_ms
        )
        self._provider = MeterProvider(metric_readers=[self._reader])
        self._meter = self._provider.get_meter(meter_name)
        self._registered: set = set()
        self.refresh_instruments()

    # -- instrument management -------------------------------------------

    def refresh_instruments(self):
        """Register an observable instrument per known metric; callbacks
        read the registry live at each export tick. Call again after new
        metrics appear (cheap; already-seen names are skipped)."""
        from opentelemetry.metrics import CallbackOptions, Observation  # noqa: F401

        from ray_tpu.util.metrics import registry

        for snap in registry().snapshot():
            name, mtype = snap["name"], snap["type"]
            if name in self._registered:
                continue
            self._registered.add(name)
            if mtype == "counter":
                self._meter.create_observable_counter(
                    name, callbacks=[self._value_callback(name)],
                    description=snap.get("help", ""),
                )
            elif mtype == "gauge":
                self._meter.create_observable_gauge(
                    name, callbacks=[self._value_callback(name)],
                    description=snap.get("help", ""),
                )
            elif mtype == "histogram":
                self._meter.create_observable_counter(
                    f"{name}_sum",
                    callbacks=[self._hist_callback(name, "sum")],
                )
                self._meter.create_observable_counter(
                    f"{name}_count",
                    callbacks=[self._hist_callback(name, "count")],
                )
                self._meter.create_observable_counter(
                    f"{name}_bucket",
                    callbacks=[self._hist_callback(name, "bucket")],
                )

    def _find(self, name: str) -> Optional[dict]:
        from ray_tpu.util.metrics import registry

        for snap in registry().snapshot():
            if snap["name"] == name:
                return snap
        return None

    def _value_callback(self, name: str):
        from opentelemetry.metrics import Observation

        def cb(options):
            snap = self._find(name)
            if snap is None:
                return []
            return [
                Observation(s["value"], attributes=s.get("tags", {}))
                for s in snap["samples"]
            ]

        return cb

    def _hist_callback(self, name: str, kind: str):
        from opentelemetry.metrics import Observation

        def cb(options):
            snap = self._find(name)
            if snap is None:
                return []
            out = []
            for s in snap["samples"]:
                tags = s.get("tags", {})
                if kind == "bucket":
                    cum = 0
                    for b, n in zip(snap["boundaries"], s["buckets"]):
                        cum += n
                        out.append(Observation(
                            cum, attributes={**tags, "le": str(b)}
                        ))
                    cum += s["buckets"][-1]
                    out.append(Observation(
                        cum, attributes={**tags, "le": "+Inf"}
                    ))
                else:
                    out.append(Observation(s[kind], attributes=tags))
            return out

        return cb

    # -- lifecycle --------------------------------------------------------

    def force_flush(self):
        self._reader.collect()

    def shutdown(self):
        self._provider.shutdown()


_bridge: Optional[OtelMetricsBridge] = None


def start_otel_export(exporter=None, interval_ms: int = 5_000):
    """Start (or return) the process-wide OTel bridge. ``exporter``
    defaults to the console exporter; pass an OTLP exporter for real
    collection."""
    global _bridge
    if _bridge is None:
        _bridge = OtelMetricsBridge(exporter, interval_ms)
    return _bridge


def stop_otel_export():
    global _bridge
    if _bridge is not None:
        _bridge.shutdown()
        _bridge = None
