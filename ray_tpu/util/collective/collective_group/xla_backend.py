"""The registered ``"xla"`` collective backend: reductions lowered to
jitted XLA collectives under ``shard_map`` over the group's mesh.

This is the SNIPPETS retrieval target ([1]–[3]) and the NCCL-replacement
half of the ROADMAP device-plane item: ``ray.util.collective`` groups
whose allreduce/allgather/reduce_scatter/broadcast execute as
``jax.lax.psum`` / ``lax.all_gather`` / ``lax.psum_scatter`` inside ONE
compiled program per (op, shape, dtype) — the math rides the accelerator
interconnect (ICI on a slice), not a Python loop over host buffers.

Two movement regimes share the one lowering:

- **Single-controller / CPU mesh (tier-1)**: rank tensors are exchanged
  once over the control plane (the coordinator actor, inherited from
  :class:`HostCollectiveGroup`), stacked onto the group mesh axis with
  ``jax.device_put``, and reduced by the jitted ``shard_map`` program.
  Results match the host backend bit-for-bit for exact float32 inputs —
  the parity contract ``tests/test_devstore.py`` pins.
- **Multi-controller SPMD (TPU pods)**: each process's addressable
  devices are already members of the global mesh, so the same jitted
  program IS the ICI collective and no host exchange happens — that path
  is the ``ici_*`` helpers' in-jit regime
  (``xla_collective_group.ici_allreduce`` et al.), usable today under
  ``pjit``/``shard_map``.

Fallback: a group wider than the local device count (or a jax-less
process) delegates to the host-staged parent — correctness never depends
on mesh availability.
"""
from __future__ import annotations

import logging
from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.util.collective.backend_registry import register_collective_backend
from ray_tpu.util.collective.collective_group.xla_collective_group import (
    XlaCollectiveGroup,
    _like,
    _to_host,
)
from ray_tpu.util.collective.types import (
    AllGatherOptions,
    AllReduceOptions,
    Backend,
    BroadcastOptions,
    ReduceOp,
    ReduceScatterOptions,
)

logger = logging.getLogger(__name__)

_AXIS = "col"  # the group mesh axis every lowered collective reduces over


@register_collective_backend(Backend.XLA)
class XlaBackendGroup(XlaCollectiveGroup):
    """``backend="xla"`` group. Collectives compile to ``shard_map``-ed
    ``jax.lax`` ops over a ``world_size``-device mesh; the host-staged
    parent is the explicit fallback when no such mesh exists locally."""

    def __init__(self, world_size: int, rank: int, group_name: str):
        super().__init__(world_size, rank, group_name)
        self._mesh = None
        self._mesh_tried = False
        self._jitted: Dict[tuple, Any] = {}
        # Pinned by the parity tests: how many collectives actually took
        # the lowered path (vs the host fallback).
        self.stats = {"shard_map_calls": 0, "host_fallbacks": 0}

    # ------------------------------------------------------------ mesh

    def _group_mesh(self):
        """One-axis mesh with a device per rank, built lazily and cached;
        None when this process cannot host it (the fallback signal)."""
        if self._mesh_tried:
            return self._mesh
        self._mesh_tried = True
        try:
            import jax
            from jax.sharding import Mesh

            devs = jax.devices()
            if self._world_size <= len(devs):
                self._mesh = Mesh(
                    np.array(devs[: self._world_size]), (_AXIS,)
                )
            else:
                logger.debug(
                    "collective group '%s': world_size %d exceeds local "
                    "device count %d; staying on the host backend",
                    self._group_name, self._world_size, len(devs),
                )
        except Exception as e:  # jax missing/broken: host path serves
            logger.debug("xla collective mesh unavailable: %s", e)
        return self._mesh

    def _stacked(self, values):
        """Host-exchanged per-rank tensors → one device array sharded a
        rank per mesh device along the group axis."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        stacked = np.stack([np.asarray(v) for v in values])
        return jax.device_put(
            stacked, NamedSharding(self._mesh, PartitionSpec(_AXIS))
        )

    def _lowered(self, key: tuple, build):
        fn = self._jitted.get(key)
        if fn is None:
            fn = self._jitted[key] = build()
        self.stats["shard_map_calls"] += 1
        return fn

    # ------------------------------------------------------ collectives

    def allreduce(self, tensor, opts: Optional[AllReduceOptions] = None):
        opts = opts or AllReduceOptions()
        if self._group_mesh() is None:
            self.stats["host_fallbacks"] += 1
            return super().allreduce(tensor, opts)
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        values = self._exchange(_to_host(tensor))
        op = opts.reduce_op

        def build():
            def f(x):  # block: [1, *shape]
                if op == ReduceOp.SUM:
                    r = jax.lax.psum(x, _AXIS)
                elif op == ReduceOp.AVERAGE:
                    r = jax.lax.pmean(x, _AXIS)
                elif op == ReduceOp.MAX:
                    r = jax.lax.pmax(x, _AXIS)
                elif op == ReduceOp.MIN:
                    r = jax.lax.pmin(x, _AXIS)
                else:  # PRODUCT: no pprod primitive — gather then prod
                    g = jax.lax.all_gather(x, _AXIS, axis=0, tiled=True)
                    r = jax.numpy.prod(g, axis=0, keepdims=True)
                return r[0]

            return jax.jit(shard_map(
                f, mesh=self._mesh, in_specs=P(_AXIS), out_specs=P(),
                check_rep=False,
            ))

        key = ("allreduce", op, np.asarray(values[0]).shape,
               str(np.asarray(values[0]).dtype))
        out = self._lowered(key, build)(self._stacked(values))
        return _like(np.asarray(out), tensor)

    def allgather(self, tensor, opts: Optional[AllGatherOptions] = None):
        opts = opts or AllGatherOptions()
        if self._group_mesh() is None:
            self.stats["host_fallbacks"] += 1
            return super().allgather(tensor, opts)
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        values = self._exchange(_to_host(tensor))

        def build():
            def f(x):  # block: [1, *shape] → [world, *shape] replicated
                return jax.lax.all_gather(x, _AXIS, axis=0, tiled=True)

            return jax.jit(shard_map(
                f, mesh=self._mesh, in_specs=P(_AXIS), out_specs=P(),
                check_rep=False,
            ))

        key = ("allgather", np.asarray(values[0]).shape,
               str(np.asarray(values[0]).dtype))
        out = np.asarray(self._lowered(key, build)(self._stacked(values)))
        return [_like(out[i], tensor) for i in range(self._world_size)]

    def reducescatter(self, tensor,
                      opts: Optional[ReduceScatterOptions] = None):
        opts = opts or ReduceScatterOptions()
        host = np.asarray(_to_host(tensor))
        mesh_ok = (
            self._group_mesh() is not None
            and opts.reduce_op == ReduceOp.SUM
            and host.ndim >= 1
            and host.shape[0] % self._world_size == 0
        )
        if not mesh_ok:
            # psum_scatter is a SUM over equal tiles by construction;
            # other ops / ragged splits keep host semantics exactly.
            self.stats["host_fallbacks"] += 1
            return super().reducescatter(tensor, opts)
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        values = self._exchange(host)

        def build():
            def f(x):  # block: [1, s0, ...] → [1, s0/world, ...]
                return jax.lax.psum_scatter(
                    x, _AXIS, scatter_dimension=1, tiled=True
                )

            return jax.jit(shard_map(
                f, mesh=self._mesh, in_specs=P(_AXIS), out_specs=P(_AXIS),
                check_rep=False,
            ))

        key = ("reducescatter", host.shape, str(host.dtype))
        out = np.asarray(self._lowered(key, build)(self._stacked(values)))
        # Device i's tile is chunk i of the reduced tensor; this rank
        # keeps its own chunk (host parity: array_split[rank]).
        return _like(out[self._rank], tensor)

    def broadcast(self, tensor, opts: Optional[BroadcastOptions] = None):
        opts = opts or BroadcastOptions()
        if self._group_mesh() is None:
            self.stats["host_fallbacks"] += 1
            return super().broadcast(tensor, opts)
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        root = opts.root_rank
        payload = _to_host(tensor) if self._rank == root else None
        values = self._exchange(payload)
        filled = [
            np.asarray(v) if v is not None else
            np.zeros_like(np.asarray(values[root])) for v in values
        ]

        def build():
            def f(x):  # mask-psum: root's block survives, replicated out
                idx = jax.lax.axis_index(_AXIS)
                masked = jax.numpy.where(
                    idx == root, x, jax.numpy.zeros_like(x)
                )
                return jax.lax.psum(masked, _AXIS)[0]

            return jax.jit(shard_map(
                f, mesh=self._mesh, in_specs=P(_AXIS), out_specs=P(),
                check_rep=False,
            ))

        key = ("broadcast", root, np.asarray(values[root]).shape,
               str(np.asarray(values[root]).dtype))
        out = self._lowered(key, build)(self._stacked(filled))
        return _like(np.asarray(out), tensor)
