"""Base collective group interface.

Reference analog: ``python/ray/util/collective/collective_group/
base_collective_group.py`` (BaseGroup) and the ``Communicator`` ABC
(``python/ray/experimental/channel/communicator.py:18``) — one interface so
transports stay pluggable.
"""
from __future__ import annotations

from abc import ABC, abstractmethod

from ray_tpu.util.collective.types import (
    AllGatherOptions,
    AllReduceOptions,
    BarrierOptions,
    BroadcastOptions,
    RecvOptions,
    ReduceOptions,
    ReduceScatterOptions,
    SendOptions,
)


class BaseGroup(ABC):
    def __init__(self, world_size: int, rank: int, group_name: str):
        self._world_size = world_size
        self._rank = rank
        self._group_name = group_name

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def world_size(self) -> int:
        return self._world_size

    @property
    def group_name(self) -> str:
        return self._group_name

    def destroy_group(self):
        pass

    @abstractmethod
    def allreduce(self, tensor, opts: AllReduceOptions):
        ...

    @abstractmethod
    def barrier(self, opts: BarrierOptions):
        ...

    @abstractmethod
    def reduce(self, tensor, opts: ReduceOptions):
        ...

    @abstractmethod
    def broadcast(self, tensor, opts: BroadcastOptions):
        ...

    @abstractmethod
    def allgather(self, tensor, opts: AllGatherOptions):
        ...

    @abstractmethod
    def reducescatter(self, tensor, opts: ReduceScatterOptions):
        ...

    @abstractmethod
    def send(self, tensor, opts: SendOptions):
        ...

    @abstractmethod
    def recv(self, opts: RecvOptions):
        ...
