"""XLA/ICI collective group — the TPU replacement for the reference's NCCL
backend (``python/ray/util/collective/collective_group/nccl_collective_group.py``).

Two regimes, per SURVEY.md §2.3:

1. **In-jit (the fast path)**: collectives inside compiled programs are not
   runtime calls at all — they are XLA HLO collectives emitted from sharding
   annotations or explicit ``jax.lax`` ops riding ICI. ``ici_*`` helpers below
   are thin, named wrappers usable under ``shard_map``/``pjit`` so user code
   has one vocabulary for both regimes.

2. **Out-of-jit (host-level jax arrays)**: staged device→host, exchanged over
   the control plane (DCN), and put back on device. This is the analog of the
   reference's host-mediated paths, and is only for control traffic — bulk
   data should stay inside jit.
"""
from __future__ import annotations

import numpy as np

from ray_tpu.util.collective.collective_group.host_collective_group import (
    HostCollectiveGroup,
)
from ray_tpu.util.collective.types import ReduceOp

# --------------------------------------------------------------------------
# In-jit helpers: use inside pjit/shard_map with a named mesh axis.
# --------------------------------------------------------------------------


def ici_allreduce(x, axis_name: str, op: ReduceOp = ReduceOp.SUM):
    import jax

    if op == ReduceOp.SUM:
        return jax.lax.psum(x, axis_name)
    if op == ReduceOp.AVERAGE:
        return jax.lax.pmean(x, axis_name)
    if op == ReduceOp.MAX:
        return jax.lax.pmax(x, axis_name)
    if op == ReduceOp.MIN:
        return jax.lax.pmin(x, axis_name)
    if op == ReduceOp.PRODUCT:
        # lax has no pprod; all_gather + prod is correct for zeros and
        # negatives (a log/exp trick would NaN on x <= 0)
        gathered = jax.lax.all_gather(x, axis_name)
        return jax.numpy.prod(gathered, axis=0)
    raise ValueError(f"unsupported in-jit reduce op {op}")


def ici_allgather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    import jax

    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def ici_reducescatter(x, axis_name: str, axis: int = 0):
    import jax

    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def ici_broadcast(x, axis_name: str, root: int = 0):
    """Broadcast root's shard to every member of the axis."""
    import jax

    idx = jax.lax.axis_index(axis_name)
    masked = jax.numpy.where(idx == root, x, jax.numpy.zeros_like(x))
    return jax.lax.psum(masked, axis_name)


def ici_ppermute(x, axis_name: str, perm):
    import jax

    return jax.lax.ppermute(x, axis_name, perm)


def ici_all_to_all(x, axis_name: str, split_axis: int, concat_axis: int):
    import jax

    return jax.lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=True
    )


# --------------------------------------------------------------------------
# Out-of-jit group: host staging + control-plane exchange.
# --------------------------------------------------------------------------


def _to_host(tensor) -> np.ndarray:
    import jax

    if isinstance(tensor, jax.Array):
        return np.asarray(jax.device_get(tensor))
    return np.asarray(tensor)


def _like(result: np.ndarray, template):
    import jax
    import jax.numpy as jnp

    if isinstance(template, jax.Array):
        arr = jnp.asarray(result).astype(template.dtype)
        return jax.device_put(arr, list(template.devices())[0])
    return result


class XlaCollectiveGroup(HostCollectiveGroup):
    """Host-staged collectives for jax arrays outside jit.

    Inherits the exchange machinery; overrides tensor conversion so jax
    arrays round-trip device→host→device and land back on their device.
    NOTE: no longer the registered ``"xla"`` backend — that is
    :class:`xla_backend.XlaBackendGroup`, which lowers the reductions to
    jitted ``shard_map`` collectives and uses THIS class as its
    host-staged fallback/base.
    """

    def allreduce(self, tensor, opts=None):
        from ray_tpu.util.collective.types import AllReduceOptions

        opts = opts or AllReduceOptions()
        out = super().allreduce(_to_host(tensor), opts)
        return _like(np.asarray(out), tensor)

    def reduce(self, tensor, opts=None):
        from ray_tpu.util.collective.types import ReduceOptions

        opts = opts or ReduceOptions()
        out = super().reduce(_to_host(tensor), opts)
        return _like(np.asarray(out), tensor)

    def broadcast(self, tensor, opts=None):
        from ray_tpu.util.collective.types import BroadcastOptions

        opts = opts or BroadcastOptions()
        out = super().broadcast(_to_host(tensor), opts)
        return _like(np.asarray(out), tensor)

    def allgather(self, tensor, opts=None):
        from ray_tpu.util.collective.types import AllGatherOptions

        opts = opts or AllGatherOptions()
        outs = super().allgather(_to_host(tensor), opts)
        return [_like(o, tensor) for o in outs]

    def reducescatter(self, tensor, opts=None):
        from ray_tpu.util.collective.types import ReduceScatterOptions

        opts = opts or ReduceScatterOptions()
        out = super().reducescatter(_to_host(tensor), opts)
        return _like(np.asarray(out), tensor)

    def send(self, tensor, opts):
        super().send(_to_host(tensor), opts)

    def recv(self, opts):
        return super().recv(opts)
