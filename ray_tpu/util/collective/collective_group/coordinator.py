"""Rendezvous + exchange coordinator actor for host-level collectives.

The reference rendezvouses NCCL unique IDs through a named actor
(``python/ray/util/collective/collective_group/nccl_util.py`` + ``GroupManager``
``collective.py:65``) and then moves data over NCCL. On TPU the accelerator
data plane is XLA-over-ICI *inside* compiled programs; host-level collectives
(rendezvous, barriers, small-tensor control traffic) ride the control plane.
This actor is that control-plane exchange point: every collective op is an
all-to-all exchange keyed by a per-group sequence number (collectives are
invoked in the same order on every rank, so a local monotone counter agrees
across ranks).
"""
from __future__ import annotations

import asyncio
from typing import Any, Dict, Tuple


class CollectiveCoordinator:
    """Named async actor; one per collective group.

    ``exchange`` implements an allgather of opaque payloads; every collective
    primitive reduces to it client-side. ``p2p_send``/``p2p_recv`` implement
    point-to-point mailboxes.
    """

    def __init__(self, world_size: int):
        self._world = world_size
        self._slots: Dict[Any, dict] = {}
        self._mail: Dict[Tuple[int, int, int], Any] = {}
        self._mail_evt: Dict[Tuple[int, int, int], asyncio.Event] = {}
        self._declared: Dict[str, int] = {}  # actor_id_hex -> rank
        self._declared_backend: str = "auto"

    def world_size(self) -> int:
        return self._world

    def declare(self, ranks_by_actor: Dict[str, int], backend: str):
        """Record the driver-side group declaration
        (``create_collective_group``) so members can lazily self-init."""
        if len(ranks_by_actor) != self._world:
            raise RuntimeError(
                f"declaring {len(ranks_by_actor)} members on a coordinator "
                f"with world_size={self._world} — a stale coordinator from a "
                f"previous group incarnation; destroy_collective_group() it "
                f"first"
            )
        self._declared = dict(ranks_by_actor)
        self._declared_backend = backend

    def lookup(self, actor_id_hex: str):
        """Rank assignment for a declared member, or None."""
        rank = self._declared.get(actor_id_hex)
        if rank is None:
            return None
        return {
            "rank": rank,
            "world_size": self._world,
            "backend": self._declared_backend,
        }

    async def exchange(self, seq: int, rank: int, payload):
        """Post ``payload`` for ``rank`` at step ``seq``; return all payloads
        (rank-ordered) once every rank has posted."""
        slot = self._slots.get(seq)
        if slot is None:
            slot = {"values": {}, "event": asyncio.Event(), "done": 0}
            self._slots[seq] = slot
        slot["values"][rank] = payload
        if len(slot["values"]) == self._world:
            slot["event"].set()
        await slot["event"].wait()
        out = [slot["values"][r] for r in range(self._world)]
        slot["done"] += 1
        if slot["done"] == self._world:
            del self._slots[seq]
        return out

    async def p2p_send(self, key: Tuple[int, int, int], payload):
        key = tuple(key)
        self._mail[key] = payload
        evt = self._mail_evt.get(key)
        if evt is None:
            evt = self._mail_evt[key] = asyncio.Event()
        evt.set()

    async def p2p_recv(self, key: Tuple[int, int, int]):
        key = tuple(key)
        evt = self._mail_evt.get(key)
        if evt is None:
            evt = self._mail_evt[key] = asyncio.Event()
        await evt.wait()
        payload = self._mail.pop(key)
        del self._mail_evt[key]
        return payload


def get_or_create_coordinator(group_name: str, world_size: int, rank: int,
                              timeout: float = 60.0):
    """All ranks create-or-get the named coordinator atomically
    (``get_if_exists`` resolves the race inside the head service)."""
    import ray_tpu

    name = f"__collective_coordinator:{group_name}"
    # num_cpus=0: pure rendezvous/IO, no compute — it must never consume a
    # CPU slot a group member needs (observed: the coordinator landing on
    # the one node that advertised a member's custom resource made that
    # member forever unschedulable).
    actor_cls = ray_tpu.remote(
        num_cpus=0, max_concurrency=max(world_size * 2, 8)
    )(CollectiveCoordinator)
    return actor_cls.options(name=name, get_if_exists=True).remote(world_size)
