"""Host-level collective group over the control plane.

TPU-era stand-in for the reference's torch-gloo backend
(``python/ray/util/collective/collective_group/torch_gloo_collective_group.py``):
small-tensor / control-plane collectives between worker processes, moved via
the coordinator actor rather than a dedicated fabric. Payloads are numpy
arrays (jax arrays are host-staged by the XLA group before delegating here).
"""
from __future__ import annotations

from typing import Any, List

import numpy as np

from ray_tpu.util.collective.backend_registry import register_collective_backend
from ray_tpu.util.collective.collective_group.base_collective_group import BaseGroup
from ray_tpu.util.collective.collective_group.coordinator import (
    get_or_create_coordinator,
)
from ray_tpu.util.collective.types import (
    AllGatherOptions,
    AllReduceOptions,
    Backend,
    BarrierOptions,
    BroadcastOptions,
    RecvOptions,
    ReduceOp,
    ReduceOptions,
    ReduceScatterOptions,
    SendOptions,
)


def _reduce(values: List[np.ndarray], op: ReduceOp) -> np.ndarray:
    stack = np.stack([np.asarray(v) for v in values])
    if op == ReduceOp.SUM:
        return stack.sum(axis=0)
    if op == ReduceOp.PRODUCT:
        return stack.prod(axis=0)
    if op == ReduceOp.MIN:
        return stack.min(axis=0)
    if op == ReduceOp.MAX:
        return stack.max(axis=0)
    if op == ReduceOp.AVERAGE:
        return stack.mean(axis=0)
    raise ValueError(f"unknown reduce op {op}")


def _copy_inplace(dst, src: np.ndarray):
    """NCCL-style in-place semantics for numpy inputs; return src otherwise."""
    if isinstance(dst, np.ndarray) and dst.shape == src.shape:
        np.copyto(dst, src.astype(dst.dtype, copy=False))
        return dst
    return src


@register_collective_backend(Backend.HOST)
class HostCollectiveGroup(BaseGroup):
    def __init__(self, world_size: int, rank: int, group_name: str):
        super().__init__(world_size, rank, group_name)
        self._coord = get_or_create_coordinator(group_name, world_size, rank)
        self._seq = 0
        self._p2p_seq = {}
        # A pre-existing coordinator (get_if_exists) may be from an older
        # incarnation with a different world size — exchanges against it
        # would hang forever, so fail loudly at init.
        import ray_tpu

        ws = ray_tpu.get(self._coord.world_size.remote(), timeout=30)
        if ws != world_size:
            raise RuntimeError(
                f"collective group '{group_name}' coordinator has "
                f"world_size={ws}, requested {world_size}; "
                f"destroy_collective_group() the old group first"
            )

    def destroy_group(self):
        """Kill the coordinator so a later re-creation of this group name
        starts from fresh state (idempotent across ranks)."""
        import ray_tpu

        try:
            ray_tpu.kill(self._coord)
        except Exception:
            pass  # another rank already killed it

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def _exchange(self, payload) -> List[Any]:
        import ray_tpu

        return ray_tpu.get(
            self._coord.exchange.remote(self._next_seq(), self._rank, payload)
        )

    # ------------------------------------------------------------- collectives

    def allreduce(self, tensor, opts: AllReduceOptions = AllReduceOptions()):
        values = self._exchange(np.asarray(tensor))
        return _copy_inplace(tensor, _reduce(values, opts.reduce_op))

    def barrier(self, opts: BarrierOptions = BarrierOptions()):
        self._exchange(None)

    def reduce(self, tensor, opts: ReduceOptions = ReduceOptions()):
        values = self._exchange(np.asarray(tensor))
        if self._rank == opts.root_rank:
            return _copy_inplace(tensor, _reduce(values, opts.reduce_op))
        return tensor

    def broadcast(self, tensor, opts: BroadcastOptions = BroadcastOptions()):
        payload = np.asarray(tensor) if self._rank == opts.root_rank else None
        values = self._exchange(payload)
        return _copy_inplace(tensor, np.asarray(values[opts.root_rank]))

    def allgather(self, tensor, opts: AllGatherOptions = AllGatherOptions()):
        return [np.asarray(v) for v in self._exchange(np.asarray(tensor))]

    def reducescatter(self, tensor, opts: ReduceScatterOptions = ReduceScatterOptions()):
        reduced = _reduce(self._exchange(np.asarray(tensor)), opts.reduce_op)
        shards = np.array_split(reduced, self._world_size, axis=0)
        return shards[self._rank]

    # ------------------------------------------------------------------- p2p

    def _p2p_key(self, src: int, dst: int):
        k = (src, dst)
        self._p2p_seq[k] = self._p2p_seq.get(k, 0) + 1
        return (src, dst, self._p2p_seq[k])

    def send(self, tensor, opts: SendOptions):
        import ray_tpu

        key = self._p2p_key(self._rank, opts.dst_rank)
        ray_tpu.get(self._coord.p2p_send.remote(key, np.asarray(tensor)))

    def recv(self, opts: RecvOptions):
        import ray_tpu

        key = self._p2p_key(opts.src_rank, self._rank)
        return np.asarray(ray_tpu.get(self._coord.p2p_recv.remote(key)))
