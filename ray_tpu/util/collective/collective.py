"""Collective group API.

Reference analog: ``python/ray/util/collective/collective.py`` —
``init_collective_group`` (:149), ``create_collective_group`` (:188),
``allreduce`` (:316), ``barrier`` (:356), ``reduce`` (:369), ``broadcast``
(:431), ``allgather`` (:481), ``reducescatter`` (:530), ``send``/``recv``
(:589/:652), ``GroupManager`` (:65).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ray_tpu.util.collective.backend_registry import get_collective_backend
from ray_tpu.util.collective.types import (
    AllGatherOptions,
    AllReduceOptions,
    Backend,
    BarrierOptions,
    BroadcastOptions,
    RecvOptions,
    ReduceOp,
    ReduceOptions,
    ReduceScatterOptions,
    SendOptions,
)


def _member_key(group_name: str) -> tuple:
    """Group membership is per *logical member* — the calling actor if any,
    else the process. (The reference assumes one actor per process; this
    runtime can colocate actors, so identity must be the actor, not the
    process.)"""
    from ray_tpu._private.worker import current_actor_id_hex

    return (current_actor_id_hex() or "__process__", group_name)


class GroupManager:
    """Per-member registry of collective groups (reference:
    ``collective.py:65``)."""

    def __init__(self):
        self._groups: Dict[tuple, object] = {}
        self._lock = threading.Lock()

    def create_group(self, backend: str, world_size: int, rank: int,
                     group_name: str):
        backend = Backend.resolve(backend)
        if backend == Backend.AUTO:
            import jax

            backend = (
                Backend.XLA if jax.default_backend() == "tpu" else Backend.HOST
            )
        cls = get_collective_backend(backend)
        key = _member_key(group_name)
        with self._lock:
            if key in self._groups:
                raise RuntimeError(
                    f"collective group '{group_name}' already initialized "
                    f"by this member"
                )
            group = cls(world_size, rank, group_name)
            self._groups[key] = group
        return group

    def get_group(self, group_name: str):
        g = self._groups.get(_member_key(group_name))
        if g is None:
            g = self._try_lazy_init(group_name)
        if g is None:
            raise RuntimeError(
                f"collective group '{group_name}' is not initialized by this "
                f"member; call init_collective_group() first (or declare it "
                f"from the driver with create_collective_group())"
            )
        return g

    def _try_lazy_init(self, group_name: str):
        """Self-init from a driver-side ``create_collective_group``
        declaration stored on the coordinator (reference behavior: the
        declarative API sets up the group without each actor calling init)."""
        import ray_tpu
        from ray_tpu._private.worker import current_actor_id_hex

        me = current_actor_id_hex()
        if me is None:
            return None
        try:
            coord = ray_tpu.get_actor(f"__collective_coordinator:{group_name}")
            spec = ray_tpu.get(coord.lookup.remote(me), timeout=30)
        except Exception:
            return None
        if spec is None:
            return None
        return self.create_group(
            spec["backend"], spec["world_size"], spec["rank"], group_name
        )

    def is_group_exist(self, group_name: str) -> bool:
        return _member_key(group_name) in self._groups

    def destroy_group(self, group_name: str):
        with self._lock:
            g = self._groups.pop(_member_key(group_name), None)
        if g is not None:
            g.destroy_group()


_group_mgr = GroupManager()


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = Backend.AUTO,
    group_name: str = "default",
):
    """Initialize this process's membership in a collective group.

    Must be called by all ``world_size`` participants (typically inside actor
    methods / tasks). Rendezvous happens through a named coordinator actor.
    """
    if world_size <= 0 or not (0 <= rank < world_size):
        raise ValueError(f"bad world_size={world_size} rank={rank}")
    _group_mgr.create_group(backend, world_size, rank, group_name)


def create_collective_group(
    actors: List,
    world_size: int,
    ranks: List[int],
    backend: str = Backend.AUTO,
    group_name: str = "default",
):
    """Declarative setup from the driver (reference: ``collective.py:188``).

    Creates the coordinator and records the actor→rank assignment on it;
    each actor then self-initializes its membership lazily on its first
    collective call (no explicit ``init_collective_group`` needed inside
    the actors).
    """
    if len(actors) != len(ranks) or len(actors) != world_size:
        raise ValueError("actors/ranks must both have world_size entries")
    if sorted(ranks) != list(range(world_size)):
        raise ValueError(f"ranks must be a permutation of 0..{world_size-1}")
    import ray_tpu
    from ray_tpu.util.collective.collective_group.coordinator import (
        get_or_create_coordinator,
    )

    coord = get_or_create_coordinator(group_name, world_size, 0)
    ranks_by_actor = {
        a._actor_id_hex: r for a, r in zip(actors, ranks)
    }
    ray_tpu.get(
        coord.declare.remote(ranks_by_actor, Backend.resolve(backend)),
        timeout=60,
    )


def destroy_collective_group(group_name: str = "default"):
    _group_mgr.destroy_group(group_name)
    # Reap the named coordinator even if this process never joined (e.g. the
    # driver after a declarative create_collective_group) so the group name
    # can be reused with fresh state.
    import ray_tpu

    try:
        coord = ray_tpu.get_actor(f"__collective_coordinator:{group_name}")
        ray_tpu.kill(coord)
    except Exception:
        pass


def is_group_initialized(group_name: str = "default") -> bool:
    return _group_mgr.is_group_exist(group_name)


def get_rank(group_name: str = "default") -> int:
    return _group_mgr.get_group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _group_mgr.get_group(group_name).world_size


def allreduce(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    return _group_mgr.get_group(group_name).allreduce(
        tensor, AllReduceOptions(reduce_op=op)
    )


def barrier(group_name: str = "default"):
    _group_mgr.get_group(group_name).barrier(BarrierOptions())


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: ReduceOp = ReduceOp.SUM):
    return _group_mgr.get_group(group_name).reduce(
        tensor, ReduceOptions(reduce_op=op, root_rank=dst_rank)
    )


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return _group_mgr.get_group(group_name).broadcast(
        tensor, BroadcastOptions(root_rank=src_rank)
    )


def allgather(tensor, group_name: str = "default"):
    return _group_mgr.get_group(group_name).allgather(tensor, AllGatherOptions())


def reducescatter(tensor, group_name: str = "default",
                  op: ReduceOp = ReduceOp.SUM):
    return _group_mgr.get_group(group_name).reducescatter(
        tensor, ReduceScatterOptions(reduce_op=op)
    )


def send(tensor, dst_rank: int, group_name: str = "default"):
    _group_mgr.get_group(group_name).send(tensor, SendOptions(dst_rank=dst_rank))


def recv(src_rank: int, group_name: str = "default"):
    return _group_mgr.get_group(group_name).recv(RecvOptions(src_rank=src_rank))
