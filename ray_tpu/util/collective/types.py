"""Collective types: reduce ops, backend names, option structs.

Reference analog: ``python/ray/util/collective/types.py``.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import List


class ReduceOp(enum.Enum):
    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3
    AVERAGE = 4


class Backend:
    """Backend name constants (reference: ``types.py Backend``).

    - ``HOST``: host-level collectives via the coordinator actor (control
      plane over DCN). Works for numpy and host-staged jax arrays. This is
      the TPU-era stand-in for the reference's torch-gloo backend.
    - ``XLA``: TPU/ICI backend (``collective_group/xla_backend.py``).
      allreduce/allgather/reduce_scatter/broadcast lower to jitted
      ``jax.lax.psum``/``lax.all_gather``/``lax.psum_scatter`` under
      ``shard_map`` over the group's mesh; in-jit collectives are
      sharding-induced XLA ops (the ``ici_*`` helpers). Cross-process
      movement outside a multi-controller mesh stages over the control
      plane. Replaces the reference's NCCL backend
      (``collective_group/nccl_collective_group.py``).
    - ``AUTO``: XLA if the input is a jax array on TPU, else HOST.
    """

    HOST = "host"
    XLA = "xla"
    AUTO = "auto"

    @staticmethod
    def resolve(name: str) -> str:
        name = (name or Backend.AUTO).lower()
        if name in ("gloo", "torch_gloo", "cpu", Backend.HOST):
            return Backend.HOST
        if name in ("nccl", "ici", "tpu", Backend.XLA):
            return Backend.XLA
        if name == Backend.AUTO:
            return Backend.AUTO
        raise ValueError(f"unknown collective backend: {name}")


@dataclass
class AllReduceOptions:
    reduce_op: ReduceOp = ReduceOp.SUM
    timeout_ms: int = 30000


@dataclass
class BarrierOptions:
    timeout_ms: int = 30000


@dataclass
class ReduceOptions:
    reduce_op: ReduceOp = ReduceOp.SUM
    root_rank: int = 0
    timeout_ms: int = 30000


@dataclass
class BroadcastOptions:
    root_rank: int = 0
    timeout_ms: int = 30000


@dataclass
class AllGatherOptions:
    timeout_ms: int = 30000


@dataclass
class ReduceScatterOptions:
    reduce_op: ReduceOp = ReduceOp.SUM
    timeout_ms: int = 30000


@dataclass
class SendOptions:
    dst_rank: int = 0
    timeout_ms: int = 30000


@dataclass
class RecvOptions:
    src_rank: int = 0
    timeout_ms: int = 30000
