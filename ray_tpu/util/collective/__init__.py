"""Collective communication across tasks/actors (reference:
``python/ray/util/collective/``), re-based on TPU physics: XLA collectives
over ICI in-jit; control-plane exchange over DCN out-of-jit."""
from ray_tpu.util.collective.backend_registry import (
    BackendRegistry,
    get_collective_backend,
    register_collective_backend,
)
from ray_tpu.util.collective.collective import (
    allgather,
    allreduce,
    barrier,
    broadcast,
    create_collective_group,
    destroy_collective_group,
    get_collective_group_size,
    get_rank,
    init_collective_group,
    is_group_initialized,
    recv,
    reduce,
    reducescatter,
    send,
)
from ray_tpu.util.collective.collective_group.xla_collective_group import (
    ici_all_to_all,
    ici_allgather,
    ici_allreduce,
    ici_broadcast,
    ici_ppermute,
    ici_reducescatter,
)
from ray_tpu.util.collective.types import Backend, ReduceOp

__all__ = [
    "init_collective_group",
    "create_collective_group",
    "destroy_collective_group",
    "is_group_initialized",
    "get_rank",
    "get_collective_group_size",
    "allreduce",
    "barrier",
    "reduce",
    "broadcast",
    "allgather",
    "reducescatter",
    "send",
    "recv",
    "ReduceOp",
    "Backend",
    "BackendRegistry",
    "register_collective_backend",
    "get_collective_backend",
    "ici_allreduce",
    "ici_allgather",
    "ici_reducescatter",
    "ici_broadcast",
    "ici_ppermute",
    "ici_all_to_all",
]
