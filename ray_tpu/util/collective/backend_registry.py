"""Pluggable collective-backend registry.

Reference analog: ``python/ray/util/collective/backend_registry.py:7``
(``BackendRegistry``, ``register_collective_backend`` :47).
"""
from __future__ import annotations

from typing import Callable, Dict


class BackendRegistry:
    """Maps backend name -> group class (lazily constructed)."""

    def __init__(self):
        self._backends: Dict[str, Callable] = {}

    def register(self, name: str, group_factory: Callable):
        if name in self._backends:
            raise ValueError(f"collective backend '{name}' already registered")
        self._backends[name] = group_factory

    def get(self, name: str) -> Callable:
        if name not in self._backends:
            raise ValueError(
                f"collective backend '{name}' not registered; "
                f"have {sorted(self._backends)}"
            )
        return self._backends[name]

    def __contains__(self, name: str) -> bool:
        return name in self._backends


_registry = BackendRegistry()


def register_collective_backend(name: str):
    """Decorator registering a group class under ``name``."""

    def deco(cls):
        _registry.register(name, cls)
        return cls

    return deco


def get_collective_backend(name: str):
    # Import built-ins lazily so registration happens on first use.
    # xla_backend (the shard_map-lowered "xla" backend) imports — and
    # falls back to — xla_collective_group's host-staged machinery.
    from ray_tpu.util.collective.collective_group import (  # noqa: F401
        host_collective_group,
        xla_backend,
    )

    return _registry.get(name)
