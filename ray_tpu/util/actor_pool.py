"""ActorPool: fan work over a fixed set of actors.

Reference analog: ``python/ray/util/actor_pool.py`` — ``map``/
``map_unordered``/``submit``/``get_next``/``get_next_unordered``.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Iterable, Iterator, List, Optional


class ActorPool:
    def __init__(self, actors: List[Any]):
        if not actors:
            raise ValueError("ActorPool requires at least one actor")
        self._idle = list(actors)
        # ref id -> (ref, actor, submit index)
        self._in_flight: dict = {}
        self._next_submit = 0
        self._next_return = 0
        self._buffered: dict = {}
        # indices taken out of order (get_next_unordered): the ordered
        # cursor must skip them or it waits forever on a consumed index
        self._consumed: set = set()

    def _advance_cursor(self, idx: int):
        self._consumed.add(idx)
        while self._next_return in self._consumed:
            self._consumed.discard(self._next_return)
            self._next_return += 1

    def submit(self, fn: Callable[[Any, Any], Any], value: Any):
        """fn(actor, value) -> ObjectRef; blocks if no actor is idle."""
        if not self._idle:
            self._wait_one()
        actor = self._idle.pop(0)
        ref = fn(actor, value)
        self._in_flight[ref.id().hex()] = (ref, actor, self._next_submit)
        self._next_submit += 1

    def has_next(self) -> bool:
        return bool(self._in_flight) or bool(self._buffered)

    def has_free(self) -> bool:
        return bool(self._idle)

    def _wait_one(self, timeout: Optional[float] = None):
        import ray_tpu

        refs = [rec[0] for rec in self._in_flight.values()]
        ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("no actor result ready in time")
        self._settle(ready[0])

    def _settle(self, ref):
        import ray_tpu

        rec = self._in_flight.pop(ref.id().hex())
        _, actor, idx = rec
        self._idle.append(actor)
        self._buffered[idx] = ref

    def get_next(self, timeout: Optional[float] = None) -> Any:
        """Next result in SUBMISSION order."""
        import ray_tpu

        if not self.has_next():
            raise StopIteration("no pending results")
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        while self._next_return not in self._buffered:
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError("no actor result ready in time")
            self._wait_one(remaining)
        idx = self._next_return
        ref = self._buffered.pop(idx)
        self._advance_cursor(idx)
        return ray_tpu.get(ref)

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        """Next COMPLETED result, any order."""
        import ray_tpu

        if not self.has_next():
            raise StopIteration("no pending results")
        if not self._buffered:
            self._wait_one(timeout)
        idx = min(self._buffered)
        ref = self._buffered.pop(idx)
        self._advance_cursor(idx)
        return ray_tpu.get(ref)

    def map(self, fn: Callable[[Any, Any], Any],
            values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]) -> Iterator[Any]:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()
