"""Placement groups (reference: ``python/ray/util/placement_group.py`` +
GCS-side 2PC in ``gcs_placement_group_scheduler.h:115``)."""
from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu._private.backoff import Backoff
from ray_tpu._private.ids import PlacementGroupID
from ray_tpu._private.worker import get_global_worker
from ray_tpu.exceptions import PlacementGroupUnavailableError


class PlacementGroup:
    def __init__(self, pg_id: str, bundles: List[Dict[str, float]], strategy: str):
        self.id = pg_id
        self.bundles = bundles
        self.strategy = strategy

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return self.bundles

    def ready(self, timeout: float = 30.0) -> bool:
        w = get_global_worker()
        deadline = time.monotonic() + timeout
        poll = Backoff(base=0.01, cap=0.25)
        while time.monotonic() < deadline:
            h = w.run_sync(w._head_call("get_pg", {"pg_id": self.id}))[0]
            if h.get("found") and h["pg"]["state"] == "CREATED":
                return True
            if h.get("found") and h["pg"]["state"] == "REMOVED":
                return False
            poll.sleep()
        return False

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        return self.ready(timeout_seconds)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundles, self.strategy))


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
    timeout: float = 30.0,
) -> PlacementGroup:
    if strategy not in ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD"):
        raise ValueError(f"invalid placement strategy {strategy}")
    if not bundles:
        raise ValueError("placement group requires at least one bundle")
    from ray_tpu._private.config import rt_config

    w = get_global_worker()
    pg_id = PlacementGroupID.from_random().hex()
    # corr: a retried create_pg after a dropped reply must replay the
    # original outcome — re-running it would overwrite the registered
    # group and leak the first commit's bundle reservations. The run_sync
    # budget covers the full retry envelope (attempts x per-attempt
    # deadline plus backoff) so a configured retry is never cut short.
    attempt_s = timeout + 15
    attempts = int(rt_config.rpc_retries) + 1
    h = w.run_sync(
        w._head_call(
            "create_pg",
            {
                "pg_id": pg_id,
                "bundles": [{k: float(v) for k, v in b.items()} for b in bundles],
                "pg_strategy": strategy,
                "name": name,
                "timeout": timeout,
            },
            timeout=attempt_s,
            corr=True,
        ),
        timeout=attempts * (attempt_s + 2) + 10,
    )[0]
    pg = PlacementGroup(pg_id, bundles, strategy)
    if h.get("state") != "CREATED":
        # Stays PENDING server-side; caller can still .ready() poll.
        pass
    return pg


def remove_placement_group(pg: PlacementGroup):
    w = get_global_worker()
    w.run_sync(w._head_call("remove_pg", {"pg_id": pg.id}))


def get_placement_group(pg_id: str) -> Optional[PlacementGroup]:
    w = get_global_worker()
    h = w.run_sync(w._head_call("get_pg", {"pg_id": pg_id}))[0]
    if not h.get("found"):
        return None
    info = h["pg"]
    return PlacementGroup(info["placement_group_id"], info["bundles"], info["strategy"])
