"""Cluster debugging: live stack dumps and memory profiling, no deps.

Reference analog: the dashboard reporter agent's profiling hooks
(``dashboard/modules/reporter/profile_manager.py`` — py-spy stack dumps /
flamegraphs, memray memory tracking) and the ``ray stack`` CLI. TPU-era
redesign: workers are CPython processes we own, so stacks come from
``sys._current_frames`` and allocation profiles from ``tracemalloc`` —
no external profilers to install, and the same RPCs work on any host.
"""
from __future__ import annotations

import sys
import threading
import traceback
from typing import Any, Dict, List, Optional


def dump_local_stacks() -> str:
    """Format every thread's current Python stack (py-spy dump analog)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: List[str] = []
    for tid, frame in sorted(sys._current_frames().items()):
        name = names.get(tid, "?")
        out.append(f"--- thread {name} (tid={tid}) ---")
        out.extend(
            line.rstrip("\n")
            for line in traceback.format_stack(frame)
        )
    return "\n".join(out)


def memory_profile_local(action: str = "snapshot", top: int = 10):
    """tracemalloc control (memray analog): action in start|stop|snapshot.
    Snapshot returns the top allocation sites since start()."""
    import tracemalloc

    if action == "start":
        if not tracemalloc.is_tracing():
            tracemalloc.start()
        return {"tracing": True}
    if action == "stop":
        if tracemalloc.is_tracing():
            tracemalloc.stop()
        return {"tracing": False}
    if action != "snapshot":
        raise ValueError(f"unknown memory_profile action {action!r}")
    if not tracemalloc.is_tracing():
        return {"tracing": False, "top": []}
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")[: max(top, 1)]
    return {
        "tracing": True,
        "top": [
            {
                "site": str(s.traceback[0]) if s.traceback else "?",
                "size_bytes": s.size,
                "count": s.count,
            }
            for s in stats
        ],
    }


# ----------------------------------------------------------- cluster-facing


def get_cluster_stacks(
    address: Optional[str] = None, include_driver: bool = True
) -> Dict[str, str]:
    """Per-node stack dumps for every alive node (reference: ``ray stack``),
    keyed by node id. With ``include_driver`` the calling process's own
    stacks are added under "driver" (off for detached tools like the CLI,
    whose stacks are noise)."""
    from ray_tpu.util.state import _call

    out = dict(_call("cluster_stacks", {}, address).get("nodes", {}))
    if include_driver:
        out["driver"] = dump_local_stacks()
    return out


def node_memory_profile(
    node_id: str, action: str = "snapshot", top: int = 10,
    address: Optional[str] = None,
) -> Dict[str, Any]:
    """Drive tracemalloc on one node: start -> (workload) -> snapshot."""
    from ray_tpu.util.state import _call

    return _call(
        "node_debug",
        {"node_id": node_id, "method": "memory_profile",
         "action": action, "top": top},
        address,
    )
