"""Cluster debugging: live stack dumps and memory profiling, no deps.

Reference analog: the dashboard reporter agent's profiling hooks
(``dashboard/modules/reporter/profile_manager.py`` — py-spy stack dumps /
flamegraphs, memray memory tracking) and the ``ray stack`` CLI. TPU-era
redesign: workers are CPython processes we own, so stacks come from
``sys._current_frames`` and allocation profiles from ``tracemalloc`` —
no external profilers to install, and the same RPCs work on any host.
"""
from __future__ import annotations

import sys
import threading
import traceback
from typing import Any, Dict, List, Optional


def dump_local_stacks() -> str:
    """Format every thread's current Python stack (py-spy dump analog)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: List[str] = []
    for tid, frame in sorted(sys._current_frames().items()):
        name = names.get(tid, "?")
        out.append(f"--- thread {name} (tid={tid}) ---")
        out.extend(
            line.rstrip("\n")
            for line in traceback.format_stack(frame)
        )
    return "\n".join(out)


def memory_profile_local(action: str = "snapshot", top: int = 10):
    """tracemalloc control (memray analog): action in start|stop|snapshot.
    Snapshot returns the top allocation sites since start()."""
    import tracemalloc

    if action == "start":
        if not tracemalloc.is_tracing():
            tracemalloc.start()
        return {"tracing": True}
    if action == "stop":
        if tracemalloc.is_tracing():
            tracemalloc.stop()
        return {"tracing": False}
    if action != "snapshot":
        raise ValueError(f"unknown memory_profile action {action!r}")
    if not tracemalloc.is_tracing():
        return {"tracing": False, "top": []}
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")[: max(top, 1)]
    return {
        "tracing": True,
        "top": [
            {
                "site": str(s.traceback[0]) if s.traceback else "?",
                "size_bytes": s.size,
                "count": s.count,
            }
            for s in stats
        ],
    }


def sample_cpu_profile(duration_s: float = 5.0, hz: float = 99.0) -> str:
    """Sampling CPU profiler (py-spy record analog, reference:
    ``dashboard/modules/reporter/profile_manager.py``): samples every
    thread's Python stack at ``hz`` for ``duration_s`` and returns
    COLLAPSED stacks ("mod:fn;mod:fn ... count" lines) — the folded
    format flamegraph.pl / speedscope / inferno consume directly. Pure
    stdlib: the sampler is a thread reading sys._current_frames, so it
    works identically in any worker we own (~1% overhead at 99Hz)."""
    import time as _time
    from collections import Counter

    interval = 1.0 / max(hz, 1.0)
    counts: Counter = Counter()
    deadline = _time.monotonic() + max(duration_s, 0.05)
    me = threading.get_ident()
    while _time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue  # the sampler's own loop is noise
            stack = []
            f = frame
            while f is not None:
                code = f.f_code
                stack.append(
                    f"{code.co_filename.rsplit('/', 1)[-1]}:"
                    f"{code.co_name}"
                )
                f = f.f_back
            counts[";".join(reversed(stack))] += 1
        _time.sleep(interval)
    return "\n".join(f"{k} {v}" for k, v in counts.most_common())


def xla_profile_capture(duration_s: float = 3.0,
                        logdir: Optional[str] = None) -> Dict[str, Any]:
    """Capture an XLA/TPU profiler trace for ``duration_s`` (the TPU-native
    profiling the reference never needed): wraps
    ``jax.profiler.start_trace/stop_trace``, producing a TensorBoard-/
    xprof-readable trace dir with device timelines, HLO op costs and HBM
    usage. Runs in the TPU-owning process — call through the node RPC for
    workers."""
    import time as _time

    try:
        import jax
    except ImportError:
        return {"ok": False, "error": "jax not importable here"}
    if logdir is None:
        import tempfile

        logdir = tempfile.mkdtemp(prefix="rt_xla_trace_")
    try:
        jax.profiler.start_trace(logdir)
        _time.sleep(max(duration_s, 0.1))
        jax.profiler.stop_trace()
    except Exception as e:
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        return {"ok": False, "error": f"{type(e).__name__}: {e}"}
    return {"ok": True, "logdir": logdir,
            "hint": "tensorboard --logdir <logdir>  (profile plugin)"}


# ----------------------------------------------------------- cluster-facing


def get_cluster_stacks(
    address: Optional[str] = None, include_driver: bool = True
) -> Dict[str, str]:
    """Per-node stack dumps for every alive node (reference: ``ray stack``),
    keyed by node id. With ``include_driver`` the calling process's own
    stacks are added under "driver" (off for detached tools like the CLI,
    whose stacks are noise)."""
    from ray_tpu.util.state import _call

    out = dict(_call("cluster_stacks", {}, address).get("nodes", {}))
    if include_driver:
        out["driver"] = dump_local_stacks()
    return out


def node_cpu_profile(
    node_id: str, duration_s: float = 5.0, hz: float = 99.0,
    address: Optional[str] = None,
) -> str:
    """Sample one node's CPU profile; returns collapsed stacks (write to a
    .folded file for flamegraph tooling)."""
    from ray_tpu.util.state import _call

    return _call(
        "node_debug",
        {"node_id": node_id, "method": "cpu_profile",
         "duration_s": duration_s, "hz": hz},
        address,
        timeout=duration_s + 60,  # the capture itself takes duration_s
    ).get("folded", "")


def node_xla_profile(
    node_id: str, duration_s: float = 3.0, logdir: Optional[str] = None,
    address: Optional[str] = None,
) -> Dict[str, Any]:
    """Capture an XLA/TPU trace on the node that owns the chips."""
    from ray_tpu.util.state import _call

    return _call(
        "node_debug",
        {"node_id": node_id, "method": "xla_profile",
         "duration_s": duration_s, "logdir": logdir},
        address,
        timeout=duration_s + 60,  # the capture itself takes duration_s
    )


def node_memory_profile(
    node_id: str, action: str = "snapshot", top: int = 10,
    address: Optional[str] = None,
) -> Dict[str, Any]:
    """Drive tracemalloc on one node: start -> (workload) -> snapshot."""
    from ray_tpu.util.state import _call

    return _call(
        "node_debug",
        {"node_id": node_id, "method": "memory_profile",
         "action": action, "top": top},
        address,
    )
