"""Distributed queue backed by an actor.

Reference analog: ``python/ray/util/queue.py`` — ``Queue`` with
put/get/put_nowait/get_nowait/qsize/empty/full semantics, usable from any
task or actor (the handle pickles).
"""
from __future__ import annotations

import asyncio
import time
from typing import Any, List, Optional


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    """Async actor hosting the buffer; awaiting consumers don't block the
    actor (max_concurrency lets puts interleave with blocked gets)."""

    def __init__(self, maxsize: int):
        self._q: "asyncio.Queue" = asyncio.Queue(maxsize)

    async def put(self, item, timeout: Optional[float] = None) -> bool:
        try:
            if timeout is None:
                await self._q.put(item)
            else:
                await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def get(self, timeout: Optional[float] = None):
        try:
            if timeout is None:
                return True, await self._q.get()
            return True, await asyncio.wait_for(self._q.get(), timeout)
        except asyncio.TimeoutError:
            return False, None

    async def put_nowait(self, item) -> bool:
        try:
            self._q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    async def get_nowait(self):
        try:
            return True, self._q.get_nowait()
        except asyncio.QueueEmpty:
            return False, None

    async def qsize(self) -> int:
        return self._q.qsize()

    async def maxsize(self) -> int:
        return self._q.maxsize


class Queue:
    def __init__(self, maxsize: int = 0, *, actor_options: Optional[dict] = None):
        import ray_tpu

        opts = dict(actor_options or {})
        opts.setdefault("max_concurrency", 64)
        # The queue actor is pure coordination; it must not hold a CPU slot
        # (on a 1-CPU cluster the default would starve the producer task).
        opts.setdefault("num_cpus", 0)
        self._actor = ray_tpu.remote(_QueueActor).options(**opts).remote(
            maxsize
        )
        self.maxsize = maxsize

    def put(self, item, block: bool = True, timeout: Optional[float] = None):
        import ray_tpu

        if not block:
            return self.put_nowait(item)
        ok = ray_tpu.get(
            self._actor.put.remote(item, timeout),
            timeout=(timeout + 30) if timeout else None,
        )
        if not ok:
            raise Full("queue put timed out")

    def put_nowait(self, item):
        import ray_tpu

        if not ray_tpu.get(self._actor.put_nowait.remote(item), timeout=30):
            raise Full("queue is full")

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        import ray_tpu

        if not block:
            return self.get_nowait()
        ok, item = ray_tpu.get(
            self._actor.get.remote(timeout),
            timeout=(timeout + 30) if timeout else None,
        )
        if not ok:
            raise Empty("queue get timed out")
        return item

    def get_nowait(self) -> Any:
        import ray_tpu

        ok, item = ray_tpu.get(self._actor.get_nowait.remote(), timeout=30)
        if not ok:
            raise Empty("queue is empty")
        return item

    def qsize(self) -> int:
        import ray_tpu

        return ray_tpu.get(self._actor.qsize.remote(), timeout=30)

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        return self.maxsize > 0 and self.qsize() >= self.maxsize

    def shutdown(self):
        import ray_tpu

        try:
            ray_tpu.kill(self._actor)
        except Exception:
            pass

    def __reduce__(self):
        return (_rebuild_queue, (self._actor, self.maxsize))


def _rebuild_queue(actor, maxsize):
    q = object.__new__(Queue)
    q._actor = actor
    q.maxsize = maxsize
    return q
