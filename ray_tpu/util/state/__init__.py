"""State API: cluster-wide listings and summaries.

Reference analog: ``python/ray/util/state/api.py`` — ``list_actors`` (:793),
``list_nodes`` (:885), ``list_tasks`` (:1020), ``list_objects`` (:1065),
``summarize_tasks`` (:1376), backed by GCS tables + the task-event store.
"""
from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional


def _call(method: str, header: dict, address: Optional[str] = None,
          timeout: float = 30.0):
    if address is not None:
        from ray_tpu._private.sync_client import SyncHeadClient

        client = SyncHeadClient(address)
        try:
            return client.call(method, header, timeout=timeout)[0]
        finally:
            client.close()
    from ray_tpu._private.worker import get_global_worker

    w = get_global_worker()
    return w.run_sync(w.gcs.call(method, header), timeout)[0]


def flight_snapshot(address: Optional[str] = None,
                    drain: bool = True) -> List[dict]:
    """Cluster-wide flight-recorder drain: the head fans ``flight_drain``
    out to every node and returns clock-annotated per-process snapshots
    (see ``ray_tpu._private.flight.merge_snapshots``)."""
    h = _call("flight_snapshot", {"drain": drain}, address, timeout=60.0)
    return h.get("snapshots", [])


def _apply_filters(rows: List[dict], filters) -> List[dict]:
    """filters: [(key, op, value)] with op in ("=", "!=")."""
    for key, op, value in filters or ():
        if op == "=":
            rows = [r for r in rows if str(r.get(key)) == str(value)]
        elif op == "!=":
            rows = [r for r in rows if str(r.get(key)) != str(value)]
        else:
            raise ValueError(f"unsupported filter op {op}")
    return rows


def list_nodes(address: Optional[str] = None, filters=None,
               limit: int = 1000) -> List[dict]:
    rows = _call("get_nodes", {}, address)["nodes"]
    return _apply_filters(rows, filters)[:limit]


def list_actors(address: Optional[str] = None, filters=None,
                limit: int = 1000) -> List[dict]:
    rows = _call("list_actors", {}, address)["actors"]
    return _apply_filters(rows, filters)[:limit]


def list_placement_groups(address: Optional[str] = None, filters=None,
                          limit: int = 1000) -> List[dict]:
    rows = _call("list_pgs", {}, address)["pgs"]
    return _apply_filters(rows, filters)[:limit]


def list_jobs(address: Optional[str] = None, filters=None,
              limit: int = 1000) -> List[dict]:
    rows = _call("list_jobs", {}, address)["jobs"]
    return _apply_filters(rows, filters)[:limit]


def list_objects(address: Optional[str] = None, filters=None,
                 limit: int = 1000) -> List[dict]:
    """Object-directory listing. Filters are applied SERVER-side (the
    head evaluates them over the flattened row — object_id/bytes/node/
    owner/spilled/task — before the limit slice, so a filtered listing
    is never starved by truncation); the local pass only covers heads
    predating the server-side path."""
    h = _call("list_objects", {
        "limit": limit,
        "filters": [list(f) for f in (filters or ())],
    }, address)
    return _apply_filters(h["objects"], filters)[:limit]


def memory_summary(address: Optional[str] = None,
                   group_by: Optional[str] = None,
                   grace_s: float = 5.0) -> Dict[str, Any]:
    """Cluster-wide object & memory accounting (the ``rt memory``
    surface): owner-attributed object rows {oid, bytes, kind, state,
    node, owner, task, fn}, per-node directory-vs-arena reconciliation,
    and leak candidates (directory entries past the grace window that no
    live process owns, stores, or borrows). See
    ``_private/memtrack.py``."""
    from ray_tpu._private import memtrack

    return memtrack.memory_summary(
        address=address, group_by=group_by, grace_s=grace_s
    )


def list_logs(address: Optional[str] = None, node_id: Optional[str] = None,
              tail: int = 1000) -> List[dict]:
    """Buffered worker log lines from the head's log plane (reference:
    ``ray logs`` / dashboard log view; fed by _private/log_monitor.py)."""
    return _call(
        "get_logs", {"node_id": node_id, "tail": tail}, address
    )["lines"]


def list_events(address: Optional[str] = None,
                source_type: Optional[str] = None,
                event_type: Optional[str] = None,
                limit: int = 100) -> List[dict]:
    """Structured export events from the head's recorder (reference: the
    aggregator's event query surface; util/events.py)."""
    return _call("export_events", {
        "limit": limit, "source_type": source_type,
        "event_type": event_type,
    }, address)["events"]


def list_tasks(address: Optional[str] = None, filters=None,
               limit: int = 1000) -> List[dict]:
    rows = _call("list_task_events", {"limit": limit}, address)["events"]
    return _apply_filters(rows, filters)[:limit]


def summarize_tasks(address: Optional[str] = None,
                    phases: bool = False) -> Dict[str, Any]:
    """Counts by (name, state) (reference: ``api.py:1376``).

    ``phases=True`` additionally joins the flight recorder's task spans
    to the task events and attaches a per-function critical-path table
    (``{fn: {phase: {count, total_s, p50_ms, p99_ms}}}``) under
    ``cluster.phases`` — requires the flight recorder to be enabled
    (``RT_FLIGHT_ENABLED=1``); empty otherwise."""
    events = list_tasks(address, limit=100_000)
    by_name: Dict[str, Counter] = {}
    for e in events:
        name = e.get("name", "unknown")
        by_name.setdefault(name, Counter())[e.get("state", "UNKNOWN")] += 1
    out = {
        "cluster": {
            "summary": {
                name: {"state_counts": dict(c)} for name, c in by_name.items()
            },
            "total_tasks": len(events),
        }
    }
    if phases:
        from ray_tpu._private import flight, taskpath

        merged = flight.merge_snapshots(
            flight_snapshot(address, drain=False)
        )
        out["cluster"]["phases"] = taskpath.phase_table(merged, events)
    return out


def task_breakdown(task_id: str, address: Optional[str] = None,
                   drain: bool = False) -> Optional[Dict[str, Any]]:
    """One task's critical path: named phase durations summing to the
    task's driver-observed wall time, residual explicit (the ``rt
    timeline --task`` surface). None when no flight span carries the id
    (recorder off, or the span aged out of the ring)."""
    from ray_tpu._private import flight, taskpath

    merged = flight.merge_snapshots(flight_snapshot(address, drain=drain))
    events = list_tasks(address, limit=100_000)
    return taskpath.task_breakdown(merged, task_id, events)


def cluster_status(address: Optional[str] = None) -> Dict[str, Any]:
    """Autoscaler-style status: totals, availability, pending demand."""
    load = _call("cluster_load", {}, address)
    total: Dict[str, float] = {}
    avail: Dict[str, float] = {}
    alive = 0
    for n in load["nodes"]:
        if not n.get("alive"):
            continue
        alive += 1
        for k, v in n.get("resources", {}).items():
            total[k] = total.get(k, 0) + v
        for k, v in n.get("available", {}).items():
            avail[k] = avail.get(k, 0) + v
    return {
        "nodes_alive": alive,
        "resources_total": total,
        "resources_available": avail,
        "pending_demands": load["pending"],
        "pending_placement_groups": load["pending_pgs"],
    }
