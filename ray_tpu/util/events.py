"""Structured export events: typed lifecycle records for external systems.

Reference analog: the reference's structured Ray-event pipeline —
``src/ray/observability/ray_event_recorder.cc`` (typed definition +
lifecycle events for actors/jobs/nodes/tasks), the export schemas
(``src/ray/protobuf/export_*.proto``), and the aggregator agent
(``dashboard/modules/aggregator/aggregator_agent.py:76``) that buffers
events and publishes them to external HTTP targets.

TPU-era design: one recorder on the head (lifecycle authority), a JSON
schema instead of protobuf (the control plane is msgpack/JSON end-to-end),
JSON-lines persistence in the session dir, and an optional HTTP POST
target (``RT_EVENT_HTTP_TARGET``) with bounded buffering + drop-oldest
backpressure — the aggregator's publish loop collapsed into the recorder
since there is no per-node agent tree to aggregate across.
"""
from __future__ import annotations

import json
import os
import threading
import time
import uuid
from collections import deque
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

# Event taxonomy (reference: export_*.proto event families)
SOURCE_TYPES = ("NODE", "ACTOR", "TASK", "JOB", "PLACEMENT_GROUP", "DRIVER")


@dataclass
class ExportEvent:
    event_id: str
    timestamp: float
    source_type: str           # one of SOURCE_TYPES
    event_type: str            # e.g. NODE_ALIVE / NODE_DEAD / ACTOR_CREATED
    entity_id: str
    message: str = ""
    attributes: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(asdict(self), separators=(",", ":"),
                          default=str)


class EventRecorder:
    """Buffers typed events, appends them to a JSON-lines file, and
    (optionally) POSTs batches to an HTTP target."""

    def __init__(self, path: Optional[str] = None,
                 http_target: Optional[str] = None,
                 max_buffer: int = 10_000,
                 flush_interval_s: float = 1.0):
        self.path = path
        self.http_target = http_target or os.environ.get(
            "RT_EVENT_HTTP_TARGET"
        )
        self._buf: deque = deque(maxlen=max_buffer)  # drop-oldest
        self._recent: deque = deque(maxlen=max_buffer)  # query window
        self._lock = threading.Lock()
        # async HTTP publishing (bounded backlog; drained by daemon thread)
        self._http_batches: deque = deque(maxlen=64)
        self._http_lock = threading.Lock()
        self._http_thread: Optional[threading.Thread] = None
        self._flush_interval = flush_interval_s
        self._last_flush = 0.0
        self._dropped = 0
        if self.path:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)

    def emit(self, source_type: str, event_type: str, entity_id: str,
             message: str = "", **attributes) -> ExportEvent:
        if source_type not in SOURCE_TYPES:
            raise ValueError(
                f"unknown source_type {source_type!r}; one of {SOURCE_TYPES}"
            )
        ev = ExportEvent(
            event_id=uuid.uuid4().hex,
            timestamp=time.time(),
            source_type=source_type,
            event_type=event_type,
            entity_id=entity_id,
            message=message,
            attributes=attributes,
        )
        with self._lock:
            if len(self._buf) == self._buf.maxlen:
                self._dropped += 1
            self._buf.append(ev)
            self._recent.append(ev)
        if time.monotonic() - self._last_flush >= self._flush_interval:
            self.flush()
        return ev

    def flush(self) -> int:
        """Drain the buffer to the JSONL sink + HTTP target. Returns the
        number of events flushed."""
        with self._lock:
            batch = list(self._buf)
            self._buf.clear()
            self._last_flush = time.monotonic()
        if not batch:
            return 0
        if self.path:
            try:
                with open(self.path, "a") as f:
                    for ev in batch:
                        f.write(ev.to_json() + "\n")
            except OSError:
                pass
        if self.http_target:
            # NEVER on the caller's thread: emit() runs on the head's
            # event loop, and a slow/unreachable target would stall the
            # whole control plane for the urlopen timeout. A dedicated
            # daemon thread drains batches (reference: the aggregator
            # agent's async publish loop).
            with self._http_lock:
                self._http_batches.append(batch)
                if self._http_thread is None or not self._http_thread.is_alive():
                    self._http_thread = threading.Thread(
                        target=self._http_drain, daemon=True,
                        name="rt-event-publish",
                    )
                    self._http_thread.start()
        return len(batch)

    def _http_drain(self):
        import urllib.request

        while True:
            with self._http_lock:
                if not self._http_batches:
                    self._http_thread = None
                    return
                batch = self._http_batches.popleft()
            try:
                req = urllib.request.Request(
                    self.http_target,
                    data=json.dumps(
                        [asdict(e) for e in batch], default=str
                    ).encode(),
                    headers={"Content-Type": "application/json"},
                )
                urllib.request.urlopen(req, timeout=5)
            except Exception:
                # External target down: events stay in the JSONL sink;
                # the reference aggregator likewise drops on publish error
                pass

    def recent(self, limit: int = 100,
               source_type: Optional[str] = None,
               event_type: Optional[str] = None) -> List[dict]:
        with self._lock:
            evs = list(self._recent)
        if source_type:
            evs = [e for e in evs if e.source_type == source_type]
        if event_type:
            evs = [e for e in evs if e.event_type == event_type]
        return [asdict(e) for e in evs[-limit:]]

    @property
    def dropped(self) -> int:
        return self._dropped

    def close(self):
        self.flush()


def read_events(path: str) -> List[dict]:
    """Parse an events.jsonl file back into dicts (ops tooling/tests)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
