"""Scheduling strategies (reference: ``python/ray/util/scheduling_strategies.py``:
PlacementGroupSchedulingStrategy :17, NodeAffinitySchedulingStrategy :43,
NodeLabelSchedulingStrategy :164)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: object  # PlacementGroup
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False

    def to_dict(self) -> dict:
        return {
            "pg_id": self.placement_group.id,
            "bundle_index": self.placement_group_bundle_index,
        }


@dataclass
class NodeAffinitySchedulingStrategy:
    node_id: str
    soft: bool = False

    def to_dict(self) -> dict:
        return {"node_id": self.node_id, "soft": self.soft}


@dataclass
class NodeLabelSchedulingStrategy:
    hard: Dict[str, str] = field(default_factory=dict)
    soft: Dict[str, str] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"labels": dict(self.hard)}


@dataclass
class SpreadSchedulingStrategy:
    def to_dict(self) -> dict:
        return {"spread": True}
