"""TPU slice reservation + multi-slice coordinator plumbing.

Reference analog: ``python/ray/util/tpu.py`` — ``SlicePlacementGroup`` /
``slice_placement_group`` (:413/:649) reserving a whole ICI-connected slice
through the ``TPU-{type}-head`` resource, per-worker resource shaping
(:134), and ``get_tpu_coordinator_env_vars`` (:205) exporting the MEGASCALE
vars that let ``jax.distributed`` span slices over DCN.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from ray_tpu.parallel.mesh import TpuSliceSpec


@dataclass
class SlicePlacementGroup:
    """A reserved ICI slice: one bundle per host, the first also pinning the
    slice-head resource so two groups can never split one slice."""

    spec: TpuSliceSpec
    pg: object  # ray_tpu.util.placement_group.PlacementGroup

    @property
    def placement_group(self):
        return self.pg

    @property
    def num_workers(self) -> int:
        return self.spec.hosts

    @property
    def chips_per_host(self) -> int:
        return self.spec.chips_per_host

    def worker_resources(self, rank: int) -> Dict[str, float]:
        """Resources a worker actor needs to land inside this slice's
        bundle ``rank`` (reference: ``util/tpu.py:134``)."""
        res = {"TPU": float(self.spec.chips_per_host)}
        if rank == 0:
            res[self.spec.head_resource()] = 1.0
        return res

    def ready(self, timeout: float = 30.0) -> bool:
        return self.pg.ready(timeout)


def slice_placement_group(
    accelerator_type: Optional[str] = None,
    *,
    spec: Optional[TpuSliceSpec] = None,
    strategy: str = "STRICT_SPREAD",
    timeout: float = 30.0,
) -> SlicePlacementGroup:
    """Reserve one whole TPU slice (reference: ``util/tpu.py:649``).

    ``accelerator_type`` like "v5e-16" (generation + total chips) or an
    explicit ``TpuSliceSpec``. Bundles: per host {TPU: chips_per_host}; the
    first bundle also takes ``TPU-{type}-head: 1`` — the slice-atomicity
    token only worker 0 of a slice advertises.
    """
    from ray_tpu.util.placement_group import placement_group

    if spec is None:
        import re

        if accelerator_type is None:
            raise ValueError("pass accelerator_type or spec")
        m = re.match(r"^(v\w+?)-(\d+)$", accelerator_type)
        if not m:
            raise ValueError(
                f"accelerator_type must look like 'v5e-16', got "
                f"{accelerator_type!r}"
            )
        gen, chips = m.group(1), int(m.group(2))
        per_host = _observed_chips_per_host(accelerator_type)
        if per_host is None:
            from ray_tpu._private.accelerators.tpu import _CHIPS_PER_HOST

            per_host = min(_CHIPS_PER_HOST.get(gen, 4), chips)
        hosts = max(chips // per_host, 1)
        spec = TpuSliceSpec(
            generation=gen, topology=(chips,), hosts=hosts,
            chips_per_host=per_host,
        )
    bundles: List[Dict[str, float]] = []
    for h in range(spec.hosts):
        b = {"TPU": float(spec.chips_per_host)}
        if h == 0:
            b[spec.head_resource()] = 1.0
        bundles.append(b)
    pg = placement_group(bundles, strategy=strategy, timeout=timeout)
    return SlicePlacementGroup(spec=spec, pg=pg)


def _observed_chips_per_host(accelerator_type: str):
    """Actual TPU count advertised by live slice nodes, if any are
    registered — the generation table is only a fallback (real slices vary:
    a v5e-16 can be 4 hosts x 4 chips or 2 x 8 depending on the VM shape)."""
    try:
        import ray_tpu

        counts = []
        for n in ray_tpu.nodes():
            if not n.get("alive"):
                continue
            labels = n.get("labels") or {}
            if labels.get("ray_tpu.accelerator_type") == accelerator_type:
                tpus = n.get("resources", {}).get("TPU")
                if tpus:
                    counts.append(int(tpus))
        if counts:
            return min(counts)
    except Exception:
        pass
    return None


def get_tpu_coordinator_env_vars(
    coordinator_address: str,
    num_slices: int,
    slice_id: int,
) -> Dict[str, str]:
    """MEGASCALE env for multi-slice DCN training (reference:
    ``util/tpu.py:205`` — consumed by jax.distributed on each host)."""
    return {
        "MEGASCALE_COORDINATOR_ADDRESS": coordinator_address,
        "MEGASCALE_NUM_SLICES": str(num_slices),
        "MEGASCALE_SLICE_ID": str(slice_id),
    }


def get_current_pod_worker_count() -> int:
    """Hosts in this pod slice (env-derived; 1 off-TPU)."""
    import os

    v = os.environ.get("TPU_WORKER_HOSTNAMES")
    if v:
        return len([h for h in v.split(",") if h])
    return 1
