"""User + internal metrics: Counter/Gauge/Histogram with Prometheus export.

Reference analog: ``ray.util.metrics`` (Counter/Gauge/Histogram backed by
``src/ray/stats/metric.h`` via ``includes/metric.pxi``) and the per-node
metrics agent → Prometheus scrape pipeline. Here every process keeps a
registry; workers push snapshots to the head with their telemetry batch, and
the dashboard exposes ``/metrics`` in Prometheus text format (one sample per
(metric, tags, worker)).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

_DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


class _Registry:
    def __init__(self):
        self._metrics: Dict[str, "Metric"] = {}
        self._lock = threading.Lock()

    def register(self, metric: "Metric") -> "Metric":
        """Returns the canonical instance for this name: re-constructing a
        metric (e.g. inside a task body run many times) must accumulate into
        the existing series, not reset it."""
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None:
                if type(existing) is not type(metric):
                    raise ValueError(
                        f"metric {metric.name} already registered with a "
                        f"different type"
                    )
                if getattr(existing, "boundaries", None) != getattr(
                    metric, "boundaries", None
                ):
                    raise ValueError(
                        f"histogram {metric.name} re-registered with "
                        f"different boundaries"
                    )
                return existing
            self._metrics[metric.name] = metric
            return metric

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [m._snapshot() for m in self._metrics.values()]

    def clear(self):
        with self._lock:
            self._metrics.clear()


_registry = _Registry()


def registry() -> _Registry:
    return _registry


def _tags_key(tags: Optional[Dict[str, str]]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((tags or {}).items()))


class Metric:
    metric_type = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        if not name:
            raise ValueError("metric name required")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._values: Dict[Tuple, float] = {}
        canonical = _registry.register(self)
        if canonical is not self:
            # share storage with the already-registered series
            self._values = canonical._values
            self._lock = canonical._lock

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _merged(self, tags):
        if self._default_tags:
            return {**self._default_tags, **(tags or {})}
        return tags or {}

    def _snapshot(self) -> dict:
        with self._lock:
            samples = [
                {"tags": dict(k), "value": v} for k, v in self._values.items()
            ]
        return {
            "name": self.name, "type": self.metric_type,
            "help": self.description, "samples": samples,
        }


class Counter(Metric):
    metric_type = "counter"

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("counters only increase")
        key = _tags_key(self._merged(tags))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value


class Gauge(Metric):
    metric_type = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        with self._lock:
            self._values[_tags_key(self._merged(tags))] = float(value)


class Histogram(Metric):
    metric_type = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Sequence[float] = _DEFAULT_BUCKETS,
                 tag_keys: Sequence[str] = ()):
        self.boundaries = tuple(sorted(boundaries))
        # per tags: (bucket counts [len+1], sum, count)
        super().__init__(name, description, tag_keys)

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = _tags_key(self._merged(tags))
        with self._lock:
            rec = self._values.get(key)
            if rec is None:
                rec = [[0] * (len(self.boundaries) + 1), 0.0, 0]
                self._values[key] = rec
            idx = len(self.boundaries)
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    idx = i
                    break
            rec[0][idx] += 1
            rec[1] += value
            rec[2] += 1

    def _snapshot(self) -> dict:
        with self._lock:
            samples = [
                {
                    "tags": dict(k),
                    "buckets": list(rec[0]),
                    "sum": rec[1],
                    "count": rec[2],
                }
                for k, rec in self._values.items()
            ]
        return {
            "name": self.name, "type": "histogram",
            "help": self.description,
            "boundaries": list(self.boundaries), "samples": samples,
        }


def _fmt_tags(tags: Dict[str, str]) -> str:
    if not tags:
        return ""
    inner = ",".join(
        f'{k}="{str(v).replace(chr(34), chr(39))}"'
        for k, v in sorted(tags.items())
    )
    return "{" + inner + "}"


def render_prometheus(snapshots: Dict[str, List[dict]],
                      exclude: Sequence[str] = ()) -> str:
    """snapshots: {worker_id: [metric snapshot dicts]} → exposition text.
    ``exclude``: metric names rendered elsewhere (e.g. the cluster-wide
    rollup of :func:`rollup_histogram`) — emitting them per-worker too
    would double-count in any scraper that sums the series."""

    fmt_tags = _fmt_tags
    lines: List[str] = []
    seen_headers = set()
    for worker_id, metrics in snapshots.items():
        for m in metrics:
            if m["name"] in exclude:
                continue
            if m["name"] not in seen_headers:
                seen_headers.add(m["name"])
                if m.get("help"):
                    lines.append(f"# HELP {m['name']} {m['help']}")
                lines.append(f"# TYPE {m['name']} {m['type']}")
            for s in m["samples"]:
                tags = {**s.get("tags", {}), "worker_id": worker_id[:12]}
                if m["type"] == "histogram":
                    cum = 0
                    for b, n in zip(m["boundaries"], s["buckets"]):
                        cum += n
                        lines.append(
                            f"{m['name']}_bucket"
                            f"{fmt_tags({**tags, 'le': str(b)})} {cum}"
                        )
                    cum += s["buckets"][-1]
                    lines.append(
                        f"{m['name']}_bucket"
                        f"{fmt_tags({**tags, 'le': '+Inf'})} {cum}"
                    )
                    lines.append(
                        f"{m['name']}_sum{fmt_tags(tags)} {s['sum']}"
                    )
                    lines.append(
                        f"{m['name']}_count{fmt_tags(tags)} {s['count']}"
                    )
                else:
                    lines.append(
                        f"{m['name']}{fmt_tags(tags)} {s['value']}"
                    )
    return "\n".join(lines) + "\n"


def rollup_gauge(snapshots: Dict[str, List[dict]], name: str,
                 node_ids: Optional[Dict[str, str]] = None,
                 agg: str = "sum") -> str:
    """Cluster-wide rollup of one gauge series, grouped by (node_id,
    tags). ``agg="sum"`` for owner-attributed series (each worker reports
    only what it owns — per-node sums never double-count);
    ``agg="max"`` for node-shared readings every process on the node
    reports identically (arena utilization, memory pressure) where a sum
    would multiply by the process count. Returns exposition text (''
    when no worker pushed the series)."""
    if agg not in ("sum", "max"):
        raise ValueError(f"agg must be 'sum' or 'max', got {agg!r}")
    merged: Dict[tuple, float] = {}
    help_text = ""
    found = False
    for wid, metrics in snapshots.items():
        node = (node_ids or {}).get(wid) or "head"
        for m in metrics:
            if m.get("name") != name or m.get("type") != "gauge":
                continue
            found = True
            help_text = help_text or m.get("help", "")
            for s in m["samples"]:
                # A sample-level "node" tag wins over the pushing
                # worker's node: owner-attributed series may account
                # bytes that physically live on another node's store
                # (a task return is owned by the driver but its segment
                # sits in the executing node's arena).
                tags = dict(s["tags"])
                snode = tags.pop("node", None) or node
                key = (str(snode)[:12], tuple(sorted(tags.items())))
                v = float(s["value"])
                if key not in merged:
                    merged[key] = v
                elif agg == "max":
                    merged[key] = max(merged[key], v)
                else:
                    merged[key] += v
    if not found:
        return ""
    lines: List[str] = []
    if help_text:
        lines.append(f"# HELP {name} {help_text}")
    lines.append(f"# TYPE {name} gauge")
    for (node, tags), v in sorted(merged.items()):
        lines.append(
            f"{name}{_fmt_tags({**dict(tags), 'node_id': node})} {v}"
        )
    return "\n".join(lines) + "\n"


def rollup_histogram(snapshots: Dict[str, List[dict]], name: str,
                     node_ids: Optional[Dict[str, str]] = None) -> str:
    """Cluster-wide rollup of one histogram series: buckets/sum/count are
    merged across every worker that pushed it, grouped by (node_id, tags)
    — so the head's single ``/metrics`` endpoint exposes one bounded
    series covering every node instead of one copy per worker process.
    Returns exposition text ('' when no worker recorded the series)."""
    merged: Dict[tuple, list] = {}
    boundaries: Optional[List[float]] = None
    help_text = ""
    for wid, metrics in snapshots.items():
        node = (node_ids or {}).get(wid) or "head"
        for m in metrics:
            if m.get("name") != name or m.get("type") != "histogram":
                continue
            if boundaries is None:
                boundaries = list(m.get("boundaries") or ())
                help_text = m.get("help", "")
            elif list(m.get("boundaries") or ()) != boundaries:
                # Boundary drift across processes (version skew): adding
                # mismatched buckets would corrupt the rollup — skip, the
                # per-worker exposition still carries the series.
                continue
            for s in m["samples"]:
                key = (str(node)[:12], tuple(sorted(s["tags"].items())))
                rec = merged.get(key)
                if rec is None:
                    merged[key] = [list(s["buckets"]), float(s["sum"]),
                                   int(s["count"])]
                else:
                    rec[0] = [a + b for a, b in zip(rec[0], s["buckets"])]
                    rec[1] += float(s["sum"])
                    rec[2] += int(s["count"])
    if not merged or boundaries is None:
        return ""
    lines: List[str] = []
    if help_text:
        lines.append(f"# HELP {name} {help_text}")
    lines.append(f"# TYPE {name} histogram")
    for (node, tags), (buckets, sum_, count) in sorted(merged.items()):
        base = {**dict(tags), "node_id": node}
        cum = 0
        for b, n in zip(boundaries, buckets):
            cum += n
            lines.append(
                f"{name}_bucket{_fmt_tags({**base, 'le': str(b)})} {cum}"
            )
        cum += buckets[-1]
        lines.append(
            f"{name}_bucket{_fmt_tags({**base, 'le': '+Inf'})} {cum}"
        )
        lines.append(f"{name}_sum{_fmt_tags(base)} {sum_}")
        lines.append(f"{name}_count{_fmt_tags(base)} {count}")
    return "\n".join(lines) + "\n"
