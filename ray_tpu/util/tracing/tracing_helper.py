"""OpenTelemetry tracing around task submission/execution.

Reference analog: ``python/ray/util/tracing/tracing_helper.py`` —
import-guarded (:36-40) span wrappers applied around submit/execute, with
trace context propagated inside task metadata. Disabled (no-op, near-zero
cost) until ``setup_tracing`` runs; the worker hot path only pays a None
check.
"""
from __future__ import annotations

from contextlib import contextmanager
from typing import Dict, Optional

_tracer = None
_propagator = None


def enabled() -> bool:
    return _tracer is not None


def setup_tracing(service_name: str = "ray_tpu",
                  exporter: Optional[object] = None,
                  in_memory: bool = False):
    """Enable tracing in THIS process. exporter: any OTel SpanExporter;
    in_memory=True installs an InMemorySpanExporter and returns it (tests).
    """
    global _tracer, _propagator
    try:
        from opentelemetry import trace
        from opentelemetry.propagate import get_global_textmap
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import SimpleSpanProcessor
    except ImportError:  # tracing stays off without the SDK
        return None

    provider = TracerProvider()
    memory_exporter = None
    if in_memory:
        from opentelemetry.sdk.trace.export.in_memory_span_exporter import (
            InMemorySpanExporter,
        )

        memory_exporter = InMemorySpanExporter()
        exporter = memory_exporter
    if exporter is not None:
        provider.add_span_processor(SimpleSpanProcessor(exporter))
    trace.set_tracer_provider(provider)
    _tracer = trace.get_tracer(service_name)
    _propagator = get_global_textmap()
    return memory_exporter


def teardown_tracing():
    global _tracer, _propagator
    _tracer = None
    _propagator = None


def inject_context() -> Optional[Dict[str, str]]:
    """Trace headers for a task being submitted (None when disabled)."""
    if _tracer is None:
        return None
    carrier: Dict[str, str] = {}
    _propagator.inject(carrier)
    return carrier or None


@contextmanager
def span(name: str, carrier: Optional[Dict[str, str]] = None,
         attributes: Optional[Dict[str, str]] = None):
    """Span around submit/execute; no-op when disabled."""
    if _tracer is None:
        yield None
        return
    from opentelemetry import context as otel_context

    token = None
    if carrier:
        ctx = _propagator.extract(carrier)
        token = otel_context.attach(ctx)
    try:
        with _tracer.start_as_current_span(name) as sp:
            for k, v in (attributes or {}).items():
                sp.set_attribute(k, v)
            yield sp
    finally:
        if token is not None:
            otel_context.detach(token)
