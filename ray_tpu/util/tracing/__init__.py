from ray_tpu.util.tracing.tracing_helper import (
    enabled,
    inject_context,
    setup_tracing,
    span,
    teardown_tracing,
)

__all__ = [
    "enabled", "inject_context", "setup_tracing", "span", "teardown_tracing",
]
