"""ObjectRef: a first-class future/handle to a value in the object plane.

Reference: ``python/ray/includes/object_ref.pxi`` + ownership model in
``src/ray/core_worker/reference_counter.h``. Each ref knows its id and its
*owner* (the worker that created it); serializing a ref inside another value
records a borrow with the owner so distributed refcounting stays correct.
"""
from __future__ import annotations

import contextvars
import threading
import weakref
from typing import Any, List, Optional

from ray_tpu._private.ids import ObjectID

# Live wire-materialized refs, interned by id bytes (reference analog: the
# per-id entry in ``reference_counter.h`` — one refcount record per object,
# however many Python handles alias it). Re-deserializing an id that is
# already live returns the SAME ObjectRef: repeated gets of a ref-dense
# container rebuild zero refs, register zero borrows, and enqueue zero
# release ops for the copies they would otherwise churn.
_live_refs: "weakref.WeakValueDictionary" = weakref.WeakValueDictionary()

# While serializing a value, collects ObjectRefs discovered inside it.
_serialization_sink: contextvars.ContextVar[Optional[List["ObjectRef"]]] = (
    contextvars.ContextVar("rt_ref_sink", default=None)
)

# While DEserializing a value, collects materialized ObjectRefs so borrow
# registration happens ONCE per value instead of once per ref (a container
# of 10k refs pays one batch-hook call, not 10k hook dispatches — the
# reference batches borrow deltas the same way, ``reference_counter.h``).
_deserialization_sink: contextvars.ContextVar[Optional[List["ObjectRef"]]] = (
    contextvars.ContextVar("rt_deser_sink", default=None)
)


def collect_refs_during(fn):
    """Run fn(), returning (result, refs_serialized_during_fn)."""
    sink: List[ObjectRef] = []
    token = _serialization_sink.set(sink)
    try:
        return fn(), sink
    finally:
        _serialization_sink.reset(token)


class ObjectRef:
    __slots__ = ("_id", "_owner", "_weakref_released", "__weakref__")

    _release_hook = None  # installed by the worker; called on __del__
    _deserialize_hook = None  # called when a ref is materialized from the wire
    # Called ONCE with the full ref list when a deserialization sink is
    # active (the worker's batched borrow registration).
    _deserialize_batch_hook = None
    _lock = threading.Lock()

    def __init__(self, object_id: ObjectID, owner_addr: Optional[tuple] = None):
        self._id = object_id
        self._owner = owner_addr
        self._weakref_released = False

    def id(self) -> ObjectID:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def binary(self) -> bytes:
        return self._id.binary()

    @property
    def owner_address(self):
        return self._owner

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        from ray_tpu._private.worker import global_worker

        return global_worker.as_future(self)

    def __await__(self):
        from ray_tpu._private.worker import global_worker

        return global_worker.as_asyncio_future(self).__await__()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __reduce__(self):
        sink = _serialization_sink.get()
        if sink is not None:
            sink.append(self)
        # Raw id bytes, not the ObjectID object: reconstructing the wrapped
        # id through pickle's reconstructor + validated __init__ costs ~2x
        # the whole ref rebuild on the 10k-nested-refs path.
        return (_deserialize_ref, (self._id.binary(), self._owner))

    def __del__(self):
        hook = ObjectRef._release_hook
        if hook is not None and not self._weakref_released:
            try:
                hook(self._id)
            except Exception:
                pass


def _deserialize_ref(id_bytes, owner: Optional[tuple]) -> ObjectRef:
    # Hot path (a value can nest 10k+ refs): raw __new__ construction skips
    # the validated initializers, and an active deserialization sink defers
    # ALL borrow bookkeeping to one batch-hook call after the load.
    if isinstance(id_bytes, ObjectID):  # pre-batching pickles (same-id wire)
        id_bytes = id_bytes.binary()
    cached = _live_refs.get(id_bytes)
    if cached is not None:
        # Already live in this process: alias it. Its borrow was registered
        # when it was first materialized and stays pinned until the LAST
        # holder drops it, so no new registration is due.
        return cached
    oid = ObjectID.__new__(ObjectID)
    oid._bytes = id_bytes
    ref = ObjectRef.__new__(ObjectRef)
    ref._id = oid
    ref._owner = owner
    ref._weakref_released = False
    try:
        _live_refs[id_bytes] = ref
    except Exception:
        pass
    sink = _deserialization_sink.get()
    if sink is not None:
        sink.append(ref)
        return ref
    hook = ObjectRef._deserialize_hook
    if hook is not None:
        try:
            hook(ref)
        except Exception:
            pass
    return ref


class StreamingObjectRefGenerator:
    """Iterator over a generator task's returns (reference: streaming
    generators, ``core_worker/task_manager.h``). ``__next__`` yields the
    next item's ObjectRef as soon as the remote generator produced it — the
    consumer processes item i while item i+1 is still being computed. When
    the task failed, the final yielded ref raises on ``get``."""

    def __init__(self, worker, task_id, owner_addr):
        self._worker = worker
        self._task_id = task_id
        self._owner_addr = tuple(owner_addr)
        self._i = 0
        self._exhausted = False

    def __iter__(self):
        return self

    def __next__(self) -> "ObjectRef":
        import asyncio

        from ray_tpu._private.ids import ObjectID

        w = self._worker
        tid_hex = self._task_id.hex()
        i = self._i

        async def wait_next():
            rec = w._task_streams.get(tid_hex)
            while True:
                oid = ObjectID.for_return(self._task_id, i).hex()
                if oid in w.memory_store:
                    return oid
                if rec is None or (
                    rec["count"] is not None and i >= rec["count"]
                ):
                    return None
                ev = rec.get("event")
                if ev is None:
                    ev = rec["event"] = asyncio.Event()
                ev.clear()
                await ev.wait()

        oid = w.run_sync(wait_next())
        if oid is None:
            self._exhausted = True
            w._task_streams.pop(tid_hex, None)  # exhausted: drop the record
            raise StopIteration
        self._i += 1
        # acknowledge consumption: the producer's credit window advances
        # (owner-side flow control — a fast generator can only run
        # _STREAM_WINDOW items ahead of this point)
        w.loop.call_soon_threadsafe(w._send_stream_credit, tid_hex, self._i)
        return ObjectRef(
            ObjectID.for_return(self._task_id, i), self._owner_addr
        )

    def __del__(self):
        # Abandoned before exhaustion: free unconsumed items, discard
        # future arrivals, and un-throttle the producer.
        if getattr(self, "_exhausted", False):
            return
        w = self._worker
        tid_hex = self._task_id.hex()
        if tid_hex not in getattr(w, "_task_streams", {}):
            return
        loop = getattr(w, "loop", None)
        if loop is None or loop.is_closed():
            return
        try:
            loop.call_soon_threadsafe(w._abandon_stream, tid_hex, self._i)
        except RuntimeError:
            pass  # loop tearing down with the process
