"""ObjectRef: a first-class future/handle to a value in the object plane.

Reference: ``python/ray/includes/object_ref.pxi`` + ownership model in
``src/ray/core_worker/reference_counter.h``. Each ref knows its id and its
*owner* (the worker that created it); serializing a ref inside another value
records a borrow with the owner so distributed refcounting stays correct.
"""
from __future__ import annotations

import contextvars
import threading
from typing import Any, List, Optional

from ray_tpu._private.ids import ObjectID

# While serializing a value, collects ObjectRefs discovered inside it.
_serialization_sink: contextvars.ContextVar[Optional[List["ObjectRef"]]] = (
    contextvars.ContextVar("rt_ref_sink", default=None)
)


def collect_refs_during(fn):
    """Run fn(), returning (result, refs_serialized_during_fn)."""
    sink: List[ObjectRef] = []
    token = _serialization_sink.set(sink)
    try:
        return fn(), sink
    finally:
        _serialization_sink.reset(token)


class ObjectRef:
    __slots__ = ("_id", "_owner", "_weakref_released", "__weakref__")

    _release_hook = None  # installed by the worker; called on __del__
    _deserialize_hook = None  # called when a ref is materialized from the wire
    _lock = threading.Lock()

    def __init__(self, object_id: ObjectID, owner_addr: Optional[tuple] = None):
        self._id = object_id
        self._owner = owner_addr
        self._weakref_released = False

    def id(self) -> ObjectID:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def binary(self) -> bytes:
        return self._id.binary()

    @property
    def owner_address(self):
        return self._owner

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        from ray_tpu._private.worker import global_worker

        return global_worker.as_future(self)

    def __await__(self):
        from ray_tpu._private.worker import global_worker

        return global_worker.as_asyncio_future(self).__await__()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __reduce__(self):
        sink = _serialization_sink.get()
        if sink is not None:
            sink.append(self)
        return (_deserialize_ref, (self._id, self._owner))

    def __del__(self):
        hook = ObjectRef._release_hook
        if hook is not None and not self._weakref_released:
            try:
                hook(self._id)
            except Exception:
                pass


def _deserialize_ref(object_id: ObjectID, owner: Optional[tuple]) -> ObjectRef:
    ref = ObjectRef(object_id, owner)
    hook = ObjectRef._deserialize_hook
    if hook is not None:
        try:
            hook(ref)
        except Exception:
            pass
    return ref


class StreamingObjectRefGenerator:
    """Iterator over a dynamic number of returns (reference: streaming generators,
    ``core_worker/task_manager.h`` generator returns)."""

    def __init__(self, refs: List[ObjectRef]):
        self._refs = list(refs)
        self._i = 0

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        if self._i >= len(self._refs):
            raise StopIteration
        ref = self._refs[self._i]
        self._i += 1
        return ref

    def __len__(self):
        return len(self._refs)
