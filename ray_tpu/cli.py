"""Command-line interface: ``python -m ray_tpu.cli`` (alias: raytpu).

Reference analog: ``python/ray/scripts/scripts.py`` — ``ray start`` (:799),
``ray stop`` (:1346), ``ray status``, ``ray job submit/logs/stop``,
``ray summary``, ``ray timeline``.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time


def _resolve_address(args) -> str:
    from ray_tpu._private.auth import adopt_token
    from ray_tpu._private.head_main import read_address_file

    if getattr(args, "auth_token", None):
        os.environ["RT_AUTH_TOKEN"] = args.auth_token
    addr = getattr(args, "address", None) or os.environ.get("RAY_TPU_ADDRESS")
    info = read_address_file()
    if addr and addr != "auto":
        # Explicit address on the head's machine: the local 0600 address
        # file supplies the token. Remote machines pass --auth-token or
        # set RT_AUTH_TOKEN.
        if info and info.get("address") == addr:
            adopt_token(info)
        return addr
    if info is None:
        print("error: no running head found (raytpu start --head)",
              file=sys.stderr)
        sys.exit(1)
    adopt_token(info)
    return info["address"]


def cmd_start(args):
    if args.head:
        cmd = [
            sys.executable, "-m", "ray_tpu._private.head_main",
            "--host", args.host, "--port", str(args.port),
            "--num-cpus", str(args.num_cpus or os.cpu_count() or 1),
            "--resources", args.resources,
            "--dashboard-port", str(args.dashboard_port),
        ]
        if args.block:
            os.execv(sys.executable, cmd)
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True)
        line = proc.stdout.readline().strip()
        try:
            info = json.loads(line)
        except json.JSONDecodeError:
            print(f"head failed to start: {line}", file=sys.stderr)
            sys.exit(1)
        print(f"head started at {info['address']} (pid {info['head_pid']})")
        if info.get("dashboard_port"):
            print(f"dashboard: http://{args.host}:{info['dashboard_port']}")
        print(f"connect with: ray_tpu.init(address='{info['address']}')")
    else:
        address = _resolve_address(args)
        host, _, port = address.rpartition(":")
        from ray_tpu._private.ids import JobID
        from ray_tpu._private.node import spawn_node

        resources = {"CPU": float(args.num_cpus or os.cpu_count() or 1)}
        resources.update(json.loads(args.resources))
        node = spawn_node((host, int(port)), JobID.from_random(), resources)
        print(f"node started (pid {node.proc.pid}) -> {address}")


def cmd_up(args):
    """Reference: ``ray up cluster.yaml`` (scripts.py:799)."""
    from ray_tpu.autoscaler import launcher

    state = launcher.up(
        args.config, wait_for_min_workers=args.wait_min_workers
    )
    print(f"cluster {state['cluster_name']!r} up at {state['address']}")
    print(f"  head pid {state['head_pid']}, monitor pid "
          f"{state['monitor_pid']}")
    print(f"connect with: ray_tpu.init(address='{state['address']}')")
    print(f"tear down with: rt down {args.config}")


def cmd_down(args):
    from ray_tpu.autoscaler import launcher

    if launcher.down(args.config):
        print("cluster torn down")
    else:
        print("no recorded cluster state; nothing to do")


def cmd_stop(args):
    from ray_tpu._private.head_main import address_file_path, read_address_file

    info = read_address_file()
    if info is None:
        print("no running head")
        return
    pids = [info.get("head_pid")] + list(info.get("node_pids", []))
    for pid in [p for p in pids if p]:
        try:
            os.kill(pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
    try:
        os.remove(address_file_path())
    except OSError:
        pass
    print(f"stopped head (pid {info.get('head_pid')})")


def cmd_status(args):
    from ray_tpu.util import state

    address = _resolve_address(args)
    status = state.cluster_status(address)
    print(json.dumps(status, indent=2, default=str))


def cmd_job_submit(args):
    from ray_tpu.job_submission import JobSubmissionClient

    import shlex

    client = JobSubmissionClient(_resolve_address(args))
    parts = args.entrypoint
    if parts and parts[0] == "--":  # argparse.REMAINDER keeps the separator
        parts = parts[1:]
    entrypoint = shlex.join(parts)
    sub_id = client.submit_job(entrypoint=entrypoint)
    print(f"submitted: {sub_id}")
    if args.wait:
        status = client.wait_until_status(sub_id, timeout=args.timeout)
        print(f"status: {status.value}")
        print(client.get_job_logs(sub_id), end="")
        sys.exit(0 if status.value == "SUCCEEDED" else 1)


def cmd_job_status(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(_resolve_address(args))
    print(json.dumps(client.get_job_info(args.submission_id), indent=2,
                     default=str))


def cmd_job_logs(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(_resolve_address(args))
    print(client.get_job_logs(args.submission_id), end="")


def cmd_job_stop(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(_resolve_address(args))
    ok = client.stop_job(args.submission_id)
    print("stopped" if ok else "not found")


def cmd_job_list(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(_resolve_address(args))
    print(json.dumps(client.list_jobs(), indent=2, default=str))


def cmd_summary(args):
    from ray_tpu.util import state

    address = _resolve_address(args)
    if args.what == "tasks":
        print(json.dumps(state.summarize_tasks(address), indent=2))
    elif args.what == "actors":
        actors = state.list_actors(address)
        by_state = {}
        for a in actors:
            by_state[a.get("state", "?")] = by_state.get(a.get("state", "?"), 0) + 1
        print(json.dumps({"actors": by_state, "total": len(actors)}, indent=2))
    else:
        nodes = state.list_nodes(address)
        print(json.dumps({"nodes": len(nodes)}, indent=2))


def cmd_logs(args):
    """Tail buffered worker logs from the head (reference: ``ray logs``)."""
    from ray_tpu.util import state

    lines = state.list_logs(
        _resolve_address(args), node_id=args.node_id, tail=args.tail
    )
    for rec in lines:
        prefix = f"(worker pid={rec.get('pid')}, node={rec['node_id'][:8]})"
        stream = sys.stderr if rec.get("stream") == "stderr" else sys.stdout
        print(f"{prefix} {rec['line']}", file=stream)


def cmd_events(args):
    """Structured export events (reference: event aggregator queries)."""
    from ray_tpu.util import state

    events = state.list_events(
        _resolve_address(args), source_type=args.source_type,
        event_type=args.event_type, limit=args.limit,
    )
    for ev in events:
        print(json.dumps(ev))


def cmd_profile(args):
    """Profile one node: sampling CPU flamegraph (collapsed stacks) or an
    XLA/TPU trace capture (reference: ray's reporter profile_manager;
    the XLA capture is the TPU-native extension)."""
    from ray_tpu.util import state
    from ray_tpu.util.debug import node_cpu_profile, node_xla_profile

    address = _resolve_address(args)
    node_id = args.node_id
    if node_id is None:
        nodes = [n for n in state.list_nodes(address) if n.get("alive")]
        if not nodes:
            print("error: no alive nodes", file=sys.stderr)
            sys.exit(1)
        node_id = nodes[0]["node_id"]
    if args.xla:
        res = node_xla_profile(
            node_id, duration_s=args.duration, logdir=args.output,
            address=address,
        )
        print(json.dumps(res, indent=2))
        sys.exit(0 if res.get("ok") else 1)
    folded = node_cpu_profile(
        node_id, duration_s=args.duration, address=address
    )
    if args.output:
        with open(args.output, "w") as f:
            f.write(folded)
        print(f"wrote collapsed stacks to {args.output} "
              f"(feed to flamegraph.pl / speedscope)")
    else:
        print(folded)


def cmd_stack(args):
    """Per-node all-thread stack dumps (reference: ``ray stack``)."""
    from ray_tpu.util.debug import get_cluster_stacks

    stacks = get_cluster_stacks(_resolve_address(args), include_driver=False)
    for node_id, text in stacks.items():
        print(f"===== node {node_id[:12]} =====")
        print(text)
        print()


def cmd_lint(args):
    """AST-based distributed-correctness analyzer (see ray_tpu/lint/)."""
    from ray_tpu.lint.cli import run

    select = ([s.strip() for s in args.select.split(",") if s.strip()]
              if args.select else None)
    sys.exit(run(
        args.paths, json_out=args.json,
        framework=True if args.framework else None, select=select,
    ))


def cmd_timeline(args):
    """Dump task events as chrome://tracing JSON (reference: ray timeline).
    ``--rpc`` interleaves flight-recorder RPC spans under the task spans —
    ONE to_chrome_trace pass over both layers, so Perfetto draws flow
    links between a task's events and every RPC span sharing its join key
    (task id / corr). ``--task <id>`` prints that task's critical-path
    phase breakdown instead (requires RT_FLIGHT_ENABLED=1)."""
    from ray_tpu._private import flight, taskpath
    from ray_tpu.util import state

    address = _resolve_address(args)
    if getattr(args, "task", None):
        b = state.task_breakdown(args.task, address)
        if b is None:
            print(f"no flight spans recorded for task {args.task} — is "
                  f"the recorder on (RT_FLIGHT_ENABLED=1), and is the id "
                  f"a full task id from `rt summary tasks` / state API?")
            sys.exit(1)
        print(taskpath.format_task_timeline(b))
        return
    events = state.list_tasks(address, limit=100_000)
    merged = taskpath.task_events_to_merged(events)
    nrpc = 0
    if getattr(args, "rpc", False):
        # drain=False: rendering a timeline must not consume the rings
        # (a follow-up `rt flight` still sees the events).
        rpc_merged = flight.merge_snapshots(
            state.flight_snapshot(address, drain=False)
        )
        nrpc = len(rpc_merged)
        merged = sorted(merged + rpc_merged, key=lambda e: e["ts"])
    trace = flight.to_chrome_trace(merged, t0=0.0)
    with open(args.output, "w") as f:
        json.dump(trace, f)
    extra = f" (+{nrpc} rpc spans)" if nrpc else ""
    print(f"wrote {len(trace)} events to {args.output}{extra}")


def cmd_memory(args):
    """Cluster-wide object & memory accounting (reference: ``ray
    memory`` joining the ownership tables to the plasma store). Prints
    owner-attributed object rows with per-node directory-vs-arena
    reconciliation; ``--group-by`` aggregates, ``--leaks`` exits
    nonzero when leak candidates exist (CI gate: directory entries past
    the grace window that no live process owns, stores, or borrows)."""
    from ray_tpu._private import memtrack

    address = _resolve_address(args)
    summary = memtrack.memory_summary(
        address=address, group_by=args.group_by, grace_s=args.grace,
    )
    if args.json:
        print(json.dumps(summary, indent=2, default=str))
    else:
        print(memtrack.format_summary(summary, limit=args.limit))
    if args.leaks:
        leaks = summary.get("leaks") or []
        if leaks:
            print(f"\nerror: {len(leaks)} leaked object(s) "
                  f"(older than {args.grace}s, owner gone, no borrower)",
                  file=sys.stderr)
            sys.exit(1)
        print("no leaked objects")


def cmd_flight(args):
    """Drain the cluster-wide RPC flight recorder into a Chrome
    trace-event JSON (load in Perfetto or chrome://tracing). Recording
    must be on (RT_FLIGHT_ENABLED=1 / _system_config flight_enabled)."""
    from ray_tpu._private import flight
    from ray_tpu.util import state

    address = _resolve_address(args)
    snaps = state.flight_snapshot(address)
    merged = flight.merge_snapshots(snaps)
    trace = flight.to_chrome_trace(merged)
    with open(args.output, "w") as f:
        json.dump(trace, f)
    procs = sorted({e["proc"] for e in merged})
    print(f"wrote {len(trace)} trace events from {len(snaps)} process(es) "
          f"{procs} to {args.output}")
    if args.attrib:
        print(flight.format_attribution(flight.attribution(merged)))
    if getattr(args, "task_attrib", False):
        from ray_tpu._private import taskpath

        events = state.list_tasks(address, limit=100_000)
        table = taskpath.phase_table(merged, events)
        if table:
            print(taskpath.format_phase_table(table))
        else:
            print("no task.* spans recorded — run a workload with "
                  "RT_FLIGHT_ENABLED=1 before draining")
    if not merged:
        print("no events recorded — enable with RT_FLIGHT_ENABLED=1 "
              "(or _system_config={'flight_enabled': True})")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="raytpu")
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("start", help="start a head or worker node")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", default=None)
    sp.add_argument("--auth-token", default=None,
                    help="cluster token for joining a remote head "
                         "(same-host joins read the 0600 address file)")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=0)
    sp.add_argument("--num-cpus", type=int, default=0)
    sp.add_argument("--resources", default="{}")
    sp.add_argument("--dashboard-port", type=int, default=0)
    sp.add_argument("--block", action="store_true")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("stop", help="stop the local head + nodes")
    sp.set_defaults(fn=cmd_stop)

    sp = sub.add_parser(
        "up", help="start a cluster from a YAML config (head + autoscaler)"
    )
    sp.add_argument("config", help="cluster YAML path")
    sp.add_argument("--wait-min-workers", type=float, default=0.0,
                    help="seconds to wait for min_workers to register")
    sp.set_defaults(fn=cmd_up)

    sp = sub.add_parser("down", help="tear down a YAML-launched cluster")
    sp.add_argument("config", help="cluster YAML path or cluster name")
    sp.set_defaults(fn=cmd_down)

    sp = sub.add_parser("status", help="cluster resource status")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_status)

    jp = sub.add_parser("job", help="job submission")
    jsub = jp.add_subparsers(dest="job_command", required=True)
    sp = jsub.add_parser("submit")
    sp.add_argument("--address", default=None)
    sp.add_argument("--wait", action="store_true")
    sp.add_argument("--timeout", type=float, default=600.0)
    sp.add_argument("entrypoint", nargs=argparse.REMAINDER)
    sp.set_defaults(fn=cmd_job_submit)
    for name, fn in (("status", cmd_job_status), ("logs", cmd_job_logs),
                     ("stop", cmd_job_stop)):
        sp = jsub.add_parser(name)
        sp.add_argument("--address", default=None)
        sp.add_argument("submission_id")
        sp.set_defaults(fn=fn)
    sp = jsub.add_parser("list")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_job_list)

    sp = sub.add_parser("summary")
    sp.add_argument("what", choices=["tasks", "actors", "nodes"])
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_summary)

    sp = sub.add_parser(
        "profile", help="CPU flamegraph sampling / XLA trace capture"
    )
    sp.add_argument("--address", default=None)
    sp.add_argument("--node-id", default=None,
                    help="default: first alive node")
    sp.add_argument("--duration", type=float, default=5.0)
    sp.add_argument("--xla", action="store_true",
                    help="capture an XLA/TPU profiler trace instead")
    sp.add_argument("--output", "-o", default=None,
                    help="collapsed-stacks file (cpu) or trace dir (xla)")
    sp.set_defaults(fn=cmd_profile)

    sp = sub.add_parser("events", help="structured export events")
    sp.add_argument("--address", default=None)
    sp.add_argument("--source-type", default=None, dest="source_type")
    sp.add_argument("--event-type", default=None, dest="event_type")
    sp.add_argument("--limit", type=int, default=100)
    sp.set_defaults(fn=cmd_events)

    sp = sub.add_parser("logs", help="tail buffered worker logs")
    sp.add_argument("--address", default=None)
    sp.add_argument("--node-id", default=None)
    sp.add_argument("--tail", type=int, default=1000)
    sp.set_defaults(fn=cmd_logs)

    sp = sub.add_parser("stack", help="all-thread stack dump of every node")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_stack)

    sp = sub.add_parser(
        "lint", help="static distributed-correctness analysis "
                     "(RT1xx: user code, RT2xx: framework self-checks)"
    )
    sp.add_argument("paths", nargs="+", help="files or directories")
    sp.add_argument("--json", action="store_true",
                    help="machine-readable findings")
    sp.add_argument("--framework", action="store_true",
                    help="run framework (Family B) rules on every file")
    sp.add_argument("--select", default=None,
                    help="comma-separated rule-id prefixes (e.g. RT2)")
    sp.set_defaults(fn=cmd_lint)

    sp = sub.add_parser("timeline")
    sp.add_argument("--address", default=None)
    sp.add_argument("--output", default="timeline.json")
    sp.add_argument("--rpc", action="store_true",
                    help="interleave flight-recorder RPC spans under the "
                         "task spans (needs RT_FLIGHT_ENABLED=1)")
    sp.add_argument("--task", default=None, metavar="TASK_ID",
                    help="print ONE task's critical-path phase breakdown "
                         "(submit → queue/lease → fn-push/kv-get → "
                         "arg-pull → exec-queue → exec → result-push → "
                         "reply-window → reply-ack, residual explicit) "
                         "instead of writing a trace")
    sp.set_defaults(fn=cmd_timeline)

    sp = sub.add_parser(
        "memory", help="cluster-wide object & memory accounting: "
                       "owner-attributed rows, per-node reconciliation, "
                       "leak candidates (`ray memory` analog)"
    )
    sp.add_argument("--address", default=None)
    sp.add_argument("--group-by", default=None, dest="group_by",
                    choices=["owner", "node", "fn", "state", "kind",
                             "task"],
                    help="aggregate rows instead of listing them")
    sp.add_argument("--leaks", action="store_true",
                    help="exit 1 when leak candidates exist (CI gate)")
    sp.add_argument("--grace", type=float, default=5.0,
                    help="leak grace window in seconds (directory "
                         "entries younger than this are never flagged)")
    sp.add_argument("--limit", type=int, default=30,
                    help="max rows/groups printed (--json prints all)")
    sp.add_argument("--json", action="store_true",
                    help="machine-readable full summary")
    sp.set_defaults(fn=cmd_memory)

    sp = sub.add_parser(
        "flight", help="drain the cross-process RPC flight recorder into "
                       "a Chrome trace-event JSON (Perfetto-loadable)"
    )
    sp.add_argument("--address", default=None)
    sp.add_argument("--output", "-o", default="flight.json")
    sp.add_argument("--attrib", action="store_true",
                    help="also print a per-verb time-attribution table")
    sp.add_argument("--task-attrib", action="store_true",
                    dest="task_attrib",
                    help="also print the per-function task phase table "
                         "(p50/p99 per phase, joined from task events)")
    sp.set_defaults(fn=cmd_flight)
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
