"""Compiled graphs (aDAG-equivalent).

Reference analog: ``python/ray/dag/`` + ``python/ray/experimental/channel/``.
"""
from ray_tpu.dag.channel import Channel, ChannelClosedError, ChannelTimeoutError
from ray_tpu.dag.compiled import CompiledDAG, CompiledDAGRef
from ray_tpu.dag.nodes import (
    ClassMethodNode,
    DAGNode,
    InputNode,
    MultiOutputNode,
)

__all__ = [
    "Channel", "ChannelClosedError", "ChannelTimeoutError",
    "CompiledDAG", "CompiledDAGRef",
    "ClassMethodNode", "DAGNode", "InputNode", "MultiOutputNode",
]
