"""Compiled graphs (aDAG-equivalent).

Reference analog: ``python/ray/dag/`` + ``python/ray/experimental/channel/``.
"""
from ray_tpu.dag.channel import Channel, ChannelClosedError, ChannelTimeoutError
from ray_tpu.dag.compiled import CompiledDAG, CompiledDAGRef
from ray_tpu.dag.nodes import (
    ClassMethodNode,
    CollectiveOutputNode,
    DAGNode,
    InputNode,
    MultiOutputNode,
    allreduce,
)

__all__ = [
    "Channel", "ChannelClosedError", "ChannelTimeoutError",
    "CompiledDAG", "CompiledDAGRef",
    "ClassMethodNode", "CollectiveOutputNode", "DAGNode", "InputNode",
    "MultiOutputNode", "allreduce",
]
