"""DAG node types + bind API.

Reference analog: ``python/ray/dag/`` — ``InputNode`` (with-block),
``ClassMethodNode`` produced by ``actor.method.bind(...)``,
``MultiOutputNode``. Nodes form a static graph over actors that either
executes eagerly (``execute``) or compiles to channel-connected per-actor
exec loops (``experimental_compile`` → ``compiled.py``).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple


class DAGNode:
    # Set by with_tensor_transport(): edges FROM this node carry
    # accelerator arrays out-of-band (DeviceChannel) instead of pickling
    # through the shm mailbox.
    _tensor_transport = False

    def with_tensor_transport(self, transport: str = "auto"):
        """Mark this node's outputs as device-array traffic (reference:
        ``experimental/channel/torch_tensor_type.py`` type hints +
        accelerator channels). On TPU the transport is the shm arena on one
        host and the native xfer plane (DCN) across hosts, landing with
        ``jax.device_put`` — there is no NCCL analog on the hosts."""
        del transport  # one transport plane; signature kept for parity
        self._tensor_transport = True
        return self

    def __init__(self, upstream_args: Tuple, upstream_kwargs: Dict[str, Any]):
        self.args = upstream_args
        self.kwargs = upstream_kwargs

    def _dag_children(self) -> List["DAGNode"]:
        out = [a for a in self.args if isinstance(a, DAGNode)]
        out += [v for v in self.kwargs.values() if isinstance(v, DAGNode)]
        return out

    # -- eager execution (uncompiled path) ----------------------------------

    def execute(self, *input_values):
        """Run the DAG once via normal actor calls (reference:
        ``DAGNode.execute`` interpreted path)."""
        cache: Dict[int, Any] = {}
        return _exec_eager(self, input_values[0] if input_values else None,
                           cache)

    def experimental_compile(self, **kwargs):
        from ray_tpu.dag.compiled import CompiledDAG

        return CompiledDAG(self, **kwargs)


def _exec_eager(node: DAGNode, input_value, cache: Dict[int, Any]):
    if id(node) in cache:
        return cache[id(node)]
    if isinstance(node, InputNode):
        result = input_value
    elif isinstance(node, MultiOutputNode):
        import ray_tpu
        from ray_tpu.object_ref import ObjectRef

        refs = [_exec_eager(a, input_value, cache) for a in node.args]
        result = [
            ray_tpu.get(r) if isinstance(r, ObjectRef) else r for r in refs
        ]
    elif isinstance(node, ClassMethodNode):
        import ray_tpu
        from ray_tpu.object_ref import ObjectRef

        args = [
            _exec_eager(a, input_value, cache) if isinstance(a, DAGNode) else a
            for a in node.args
        ]
        kwargs = {
            k: _exec_eager(v, input_value, cache) if isinstance(v, DAGNode) else v
            for k, v in node.kwargs.items()
        }
        # upstream eager results are ObjectRefs; resolve before the call so
        # actor methods see values (constants pass through untouched)
        args = [ray_tpu.get(a) if isinstance(a, ObjectRef) else a for a in args]
        kwargs = {
            k: ray_tpu.get(v) if isinstance(v, ObjectRef) else v
            for k, v in kwargs.items()
        }
        result = getattr(node.actor, node.method_name).remote(*args, **kwargs)
    elif isinstance(node, CollectiveOutputNode):
        import ray_tpu
        from ray_tpu.object_ref import ObjectRef

        # reduce once per group, then share the result among all outputs;
        # launch every contributor before blocking so they run in parallel
        refs = [
            _exec_eager(out.args[0], input_value, cache)
            for out in node.group.outputs
        ]
        values = [
            ray_tpu.get(v) if isinstance(v, ObjectRef) else v for v in refs
        ]
        reduced = reduce_values(node.group.op, values)
        for out in node.group.outputs:
            cache[id(out)] = reduced
        result = reduced
    else:
        raise TypeError(f"unknown node {node}")
    cache[id(node)] = result
    return result


class InputNode(DAGNode):
    """The DAG's input placeholder; used as a with-block (reference:
    ``dag/input_node.py``)."""

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class ClassMethodNode(DAGNode):
    def __init__(self, actor, method_name: str, args: Tuple,
                 kwargs: Dict[str, Any]):
        super().__init__(args, kwargs)
        self.actor = actor
        self.method_name = method_name

    def __repr__(self):
        return f"ClassMethodNode({self.method_name} on {self.actor})"


class MultiOutputNode(DAGNode):
    def __init__(self, outputs: List[DAGNode]):
        super().__init__(tuple(outputs), {})


# ---------------------------------------------------------- in-DAG collectives


def reduce_values(op: str, values: List[Any]):
    """Elementwise pytree reduction used by in-DAG allreduce (host-side —
    the compiled-graph channel plane; in-jit collectives use XLA psum)."""
    import jax
    import numpy as np

    def combine(*leaves):
        stack = np.stack([np.asarray(x) for x in leaves])
        if op == "sum":
            out = stack.sum(0)
        elif op == "mean":
            out = stack.mean(0)
        elif op == "max":
            out = stack.max(0)
        elif op == "min":
            out = stack.min(0)
        else:
            raise ValueError(f"unknown allreduce op {op!r}")
        return out if out.ndim else out.item()

    return jax.tree.map(combine, *values)


class CollectiveOutputNode(DAGNode):
    """One participant's output of an in-DAG allreduce (reference:
    ``dag/collective_node.py:23 _CollectiveOperation``). Created via
    ``allreduce.bind([...])``; lives on the same actor as its contributor."""

    def __init__(self, upstream: "ClassMethodNode", group: "_CollectiveGroup",
                 index: int):
        super().__init__((upstream,), {})
        self.actor = upstream.actor
        self.group = group
        self.index = index

    def __repr__(self):
        return (
            f"CollectiveOutputNode({self.group.op} #{self.index}"
            f"/{len(self.group.outputs)})"
        )


class _CollectiveGroup:
    def __init__(self, op: str):
        self.op = op
        self.outputs: List[CollectiveOutputNode] = []


class _AllReduce:
    """``allreduce.bind(nodes, op=...)`` — returns one output node per
    participant; participants must be method nodes on distinct actors."""

    @staticmethod
    def bind(nodes: List["ClassMethodNode"], op: str = "sum"
             ) -> List["CollectiveOutputNode"]:
        if not nodes:
            raise ValueError("allreduce.bind requires at least one node")
        for n in nodes:
            if not isinstance(n, ClassMethodNode):
                raise ValueError(
                    "allreduce participants must be actor method nodes"
                )
        # distinctness by actor ID, not handle identity: get_actor() and
        # deserialization mint fresh handle objects for the same actor, and
        # two group members on one actor would deadlock its exec loop
        actors = {n.actor._actor_id for n in nodes}
        if len(actors) != len(nodes):
            raise ValueError(
                "allreduce participants must be on distinct actors"
            )
        group = _CollectiveGroup(op)
        group.outputs = [
            CollectiveOutputNode(n, group, i) for i, n in enumerate(nodes)
        ]
        return list(group.outputs)


allreduce = _AllReduce()
