"""Compiled DAG: static actor graphs with pre-allocated shm channels.

Reference analog: ``python/ray/dag/compiled_dag_node.py`` (``CompiledDAG``
:804, per-actor executable tasks :477, exec loop :185). The graph is
compiled ONCE into per-actor execution loops connected by 1-slot shm
channels (``channel.py``); per-step scheduler/RPC overhead disappears, and
the per-edge backpressure gives pipeline-parallel microbatch semantics for
free: actor A can run step t+1 while actor B runs step t.

TPU story: each actor's task list is normal Python — when the methods are
jitted jax programs the loop becomes "read host buffer → device_put → run
compiled XLA → host → write", i.e. the per-stage body of a PP schedule. On a
mesh, stages use jax transfer collectives inside one program instead
(parallel/pipeline.py); the channel path is the host/DCN fallback.
"""
from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.dag.channel import (
    Channel,
    ChannelClosedError,
    ChannelTimeoutError,
    DEFAULT_CAPACITY,
    DeviceChannel,
)

from ray_tpu.dag.nodes import (
    ClassMethodNode,
    CollectiveOutputNode,
    DAGNode,
    InputNode,
    MultiOutputNode,
    reduce_values,
)

_DEV_PREFIX = "/rt_dch_"


def open_channel(name: str, capacity: int = DEFAULT_CAPACITY,
                 create: bool = False):
    """Channel kind rides the name: device channels (tensor-transport
    edges) vs plain shm mailboxes."""
    if name.startswith(_DEV_PREFIX):
        return DeviceChannel(name, capacity=capacity, create=create)
    return Channel(name, capacity=capacity, create=create)


def _dag_actor_loop(instance, plan: dict):
    """Runs ON the actor (via __rt_apply__): the compiled exec loop."""
    import traceback

    from ray_tpu._private.worker import get_global_worker

    ctx = get_global_worker().ctx
    chans = {name: open_channel(name) for name in plan["channels"]}
    try:
        while True:
            for task in plan["tasks"]:
                if task.get("trigger"):
                    chans[task["trigger"]].read(ctx)  # step gate; value unused
                args = [
                    chans[spec[1]].read(ctx) if spec[0] == "ch" else spec[1]
                    for spec in task["args"]
                ]
                kind = task.get("kind", "call")
                if kind == "call":
                    kwargs = {
                        k: chans[spec[1]].read(ctx)
                        if spec[0] == "ch" else spec[1]
                        for k, spec in task["kwargs"].items()
                    }
                    result = getattr(instance, task["method"])(*args, **kwargs)
                elif kind == "coll_member":
                    # contribute, then wait for the leader's reduction
                    chans[task["contrib"]].write(args[0], ctx)
                    result = chans[task["result"]].read(ctx)
                elif kind == "coll_leader":
                    values = [args[0]] + [
                        chans[c].read(ctx) for c in task["contribs"]
                    ]
                    result = reduce_values(task["op"], values)
                    for r in task["results"]:
                        chans[r].write(result, ctx)
                else:
                    raise ValueError(f"unknown task kind {kind!r}")
                for out in task["out"]:
                    chans[out].write(result, ctx)
    except ChannelClosedError:
        return "torn_down"
    except Exception as e:
        # A user-method error must reach the driver, not hang it: stop every
        # channel this actor touches (readers/writers unblock with
        # ChannelClosedError) and return the traceback for _raise_loop_error.
        for ch in chans.values():
            try:
                ch.set_stop()
            except Exception:
                pass
        return {"error": f"{type(e).__name__}: {e}",
                "traceback": traceback.format_exc()}


class CompiledDAGRef:
    """Future for one execute() (reference: ``CompiledDAGRef``)."""

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq
        self._taken = False

    def get(self, timeout: Optional[float] = 60.0):
        if self._taken:
            raise ValueError("CompiledDAGRef.get() may only be called once")
        self._taken = True
        return self._dag._fetch(self._seq, timeout)


class CompiledDAGFuture:
    """Awaitable result of execute_async() (reference:
    ``CompiledDAGFuture`` — aDAG asyncio integration). Awaiting runs the
    blocking channel read in the default executor so the event loop stays
    free; like CompiledDAGRef, a result may be awaited only once."""

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq
        self._taken = False

    def __await__(self):
        import asyncio

        if self._taken:
            raise ValueError(
                "CompiledDAGFuture may only be awaited once"
            )
        self._taken = True
        loop = asyncio.get_event_loop()
        # 60s: the RESULT deadline (same default as CompiledDAGRef.get),
        # not the dag's submit_timeout — submission and step duration are
        # unrelated budgets
        return loop.run_in_executor(
            None, self._dag._fetch, self._seq, 60.0
        ).__await__()


class CompiledDAG:
    def __init__(self, root: DAGNode, channel_capacity: int = DEFAULT_CAPACITY,
                 submit_timeout: float = 60.0):
        self._capacity = channel_capacity
        self._timeout = submit_timeout
        self._torn_down = False

        # ---- walk the graph: topo order, single InputNode ----
        order: List[DAGNode] = []
        seen: Dict[int, bool] = {}

        def visit(n: DAGNode):
            if id(n) in seen:
                if not seen[id(n)]:
                    raise ValueError("cycle in DAG")
                return
            seen[id(n)] = False
            for c in n._dag_children():
                visit(c)
            seen[id(n)] = True
            order.append(n)

        visit(root)
        self._input_node = next(
            (n for n in order if isinstance(n, InputNode)), None
        )
        inputs = [n for n in order if isinstance(n, InputNode)]
        if len(inputs) > 1:
            raise ValueError("a DAG may have at most one InputNode")
        self._is_multi = isinstance(root, MultiOutputNode)
        self._outputs: List[DAGNode] = (
            list(root.args) if self._is_multi else [root]
        )
        for out in self._outputs:
            if not isinstance(out, (ClassMethodNode, CollectiveOutputNode)):
                raise ValueError("DAG outputs must be actor method nodes")
        # Every output of an allreduce group must be reachable: a dropped
        # participant would never contribute and the leader would block.
        in_order = {id(n) for n in order}
        for n in order:
            if isinstance(n, CollectiveOutputNode):
                for sibling in n.group.outputs:
                    if id(sibling) not in in_order:
                        raise ValueError(
                            "all outputs of an allreduce group must be "
                            "consumed in the DAG (participant "
                            f"#{sibling.index} is unreachable)"
                        )

        # ---- allocate channels: one per (producer → consumer) edge ----
        self._channels: Dict[str, Channel] = {}
        # producer node id -> list of channel names it must write
        out_chs: Dict[int, List[str]] = {}
        # (consumer node id, position) -> channel name
        in_ch: Dict[Tuple[int, Any], str] = {}
        self._input_chs: List[str] = []

        def new_channel(device: bool = False) -> str:
            prefix = _DEV_PREFIX if device else "/rt_ch_"
            name = f"{prefix}{uuid.uuid4().hex[:16]}"
            self._channels[name] = open_channel(
                name, capacity=self._capacity, create=True
            )
            return name

        trigger_ch: Dict[int, str] = {}
        producer_types = (ClassMethodNode, CollectiveOutputNode)
        for n in order:
            if not isinstance(n, producer_types):
                continue
            has_upstream = False
            for pos, a in enumerate(n.args):
                if isinstance(a, InputNode):
                    ch = new_channel()
                    self._input_chs.append(ch)
                    in_ch[(id(n), pos)] = ch
                    has_upstream = True
                elif isinstance(a, producer_types):
                    ch = new_channel(device=a._tensor_transport)
                    out_chs.setdefault(id(a), []).append(ch)
                    in_ch[(id(n), pos)] = ch
                    has_upstream = True
            for k, v in n.kwargs.items():
                if isinstance(v, InputNode):
                    ch = new_channel()
                    self._input_chs.append(ch)
                    in_ch[(id(n), k)] = ch
                    has_upstream = True
                elif isinstance(v, producer_types):
                    ch = new_channel(device=v._tensor_transport)
                    out_chs.setdefault(id(v), []).append(ch)
                    in_ch[(id(n), k)] = ch
                    has_upstream = True
            if isinstance(n, CollectiveOutputNode):
                continue  # collective tasks always have an upstream edge
            if not has_upstream:
                # Constant-only task: without an upstream edge its exec loop
                # would free-run ahead of execute() (side effects firing with
                # no submit). Gate every iteration on a driver trigger.
                ch = new_channel()
                self._input_chs.append(ch)
                trigger_ch[id(n)] = ch
        self._output_chs: List[str] = []
        for out in self._outputs:
            ch = new_channel(device=out._tensor_transport)
            out_chs.setdefault(id(out), []).append(ch)
            self._output_chs.append(ch)

        # ---- collective-group internal channels (contribution + result) ----
        # leader = participant 0's actor: members send contributions to it,
        # it reduces and broadcasts results back (star topology over shm;
        # reference: _CollectiveOperation lowering onto NCCL — here the
        # channel plane is the host/DCN transport).
        coll_chs: Dict[int, dict] = {}  # id(group) -> {"m": [...], "r": [...]}
        for n in order:
            if isinstance(n, CollectiveOutputNode) and n.index == 0:
                group = n.group
                members = len(group.outputs) - 1
                coll_chs[id(group)] = {
                    "m": [new_channel() for _ in range(members)],
                    "r": [new_channel() for _ in range(members)],
                }

        # ---- per-actor plans (tasks stay in global topo order) ----
        plans: Dict[str, dict] = {}
        actors: Dict[str, Any] = {}
        for n in order:
            if not isinstance(n, producer_types):
                continue
            aid = n.actor._actor_id
            actors[aid] = n.actor
            plan = plans.setdefault(aid, {"tasks": [], "channels": set()})
            arg_specs = []
            for pos, a in enumerate(n.args):
                if isinstance(a, DAGNode):
                    ch = in_ch[(id(n), pos)]
                    arg_specs.append(("ch", ch))
                    plan["channels"].add(ch)
                else:
                    arg_specs.append(("val", a))
            outs = out_chs.get(id(n), [])
            plan["channels"].update(outs)
            if isinstance(n, CollectiveOutputNode):
                group_chs = coll_chs[id(n.group)]
                if n.index == 0:
                    task = {
                        "kind": "coll_leader",
                        "args": arg_specs,
                        "kwargs": {},
                        "op": n.group.op,
                        "contribs": group_chs["m"],
                        "results": group_chs["r"],
                        "out": outs,
                        "trigger": None,
                    }
                    plan["channels"].update(group_chs["m"])
                    plan["channels"].update(group_chs["r"])
                else:
                    task = {
                        "kind": "coll_member",
                        "args": arg_specs,
                        "kwargs": {},
                        "contrib": group_chs["m"][n.index - 1],
                        "result": group_chs["r"][n.index - 1],
                        "out": outs,
                        "trigger": None,
                    }
                    plan["channels"].add(task["contrib"])
                    plan["channels"].add(task["result"])
                plan["tasks"].append(task)
                continue
            kwarg_specs = {}
            for k, v in n.kwargs.items():
                if isinstance(v, DAGNode):
                    ch = in_ch[(id(n), k)]
                    kwarg_specs[k] = ("ch", ch)
                    plan["channels"].add(ch)
                else:
                    kwarg_specs[k] = ("val", v)
            trig = trigger_ch.get(id(n))
            if trig is not None:
                plan["channels"].add(trig)
            plan["tasks"].append({
                "method": n.method_name,
                "args": arg_specs,
                "kwargs": kwarg_specs,
                "out": outs,
                "trigger": trig,
            })

        # ---- install exec loops ----
        from ray_tpu.actor import ActorMethod

        self._loop_refs = []
        for aid, plan in plans.items():
            plan["channels"] = sorted(plan["channels"])
            self._loop_refs.append(
                ActorMethod(actors[aid], "__rt_apply__").remote(
                    _dag_actor_loop, plan
                )
            )
        import threading as _threading

        self._submit_lock = _threading.Lock()
        self._fetch_lock = _threading.Lock()
        self._next_submit = 0
        self._next_fetch = 0
        self._buffered: Dict[int, Any] = {}
        self._partial: List[Any] = []  # outputs read so far for the step
        self._loop_results: List[Any] = []

    # ------------------------------------------------------------------ API

    def execute(self, *input_values) -> CompiledDAGRef:
        if self._torn_down:
            raise RuntimeError("compiled DAG torn down")
        value = input_values[0] if input_values else None
        # same lock as execute_async: mixing the APIs must not interleave
        # channel writes or race the seq counter
        with self._submit_lock:
            for ch in self._input_chs:
                self._channels[ch].write(value, timeout=self._timeout)
            seq = self._next_submit
            self._next_submit += 1
        return CompiledDAGRef(self, seq)

    async def execute_async(self, *input_values) -> "CompiledDAGFuture":
        """asyncio twin of execute() (reference: compiled_dag_node.py
        execute_async): submission happens off-loop (channel writes can
        block when the pipeline is full) and the returned future is
        awaited for the result."""
        import asyncio

        if self._torn_down:
            raise RuntimeError("compiled DAG torn down")
        value = input_values[0] if input_values else None
        loop = asyncio.get_running_loop()
        lock = self._submit_lock

        def _submit():
            # lock taken INSIDE the executor thread (never across an
            # await): concurrent execute_async calls serialize their
            # channel writes + seq assignment atomically
            with lock:
                for ch in self._input_chs:
                    self._channels[ch].write(value, timeout=self._timeout)
                seq = self._next_submit
                self._next_submit += 1
            return seq

        seq = await loop.run_in_executor(None, _submit)
        return CompiledDAGFuture(self, seq)

    def _fetch(self, seq: int, timeout: Optional[float]):
        with self._fetch_lock:
            return self._fetch_locked(seq, timeout)

    def _fetch_locked(self, seq: int, timeout: Optional[float]):
        if seq in self._buffered:
            return self._buffered.pop(seq)
        if seq < self._next_fetch:
            raise ValueError(f"result {seq} already consumed")
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            # Resume a partially-read step: a timeout mid-step must not drop
            # consumed outputs or the channels would go off-by-one forever.
            while len(self._partial) < len(self._output_chs):
                ch = self._output_chs[len(self._partial)]
                t = None if deadline is None else max(
                    deadline - time.monotonic(), 0
                )
                try:
                    self._partial.append(self._channels[ch].read(timeout=t))
                except ChannelClosedError:
                    self._raise_loop_error()
                    raise
            outs, self._partial = self._partial, []
            got = self._next_fetch
            self._next_fetch += 1
            # list iff the user built a MultiOutputNode (matches eager path,
            # including the single-output case)
            value = outs if self._is_multi else outs[0]
            if got == seq:
                return value
            self._buffered[got] = value
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeoutError(f"result {seq} not produced in time")

    def _raise_loop_error(self):
        """A stopped channel outside teardown usually means an exec loop
        died on a user exception — tear down, then surface the actor-side
        traceback collected from the loop results."""
        self.teardown()
        for res in self._loop_results:
            if isinstance(res, dict) and "error" in res:
                raise RuntimeError(
                    f"compiled DAG task failed: {res['error']}\n"
                    f"{res.get('traceback', '')}"
                )

    def teardown(self):
        if self._torn_down:
            return
        self._torn_down = True
        for ch in self._channels.values():
            ch.set_stop()
        import ray_tpu

        self._loop_results = []
        for ref in self._loop_refs:
            try:
                self._loop_results.append(ray_tpu.get(ref, timeout=30))
            except Exception:
                pass
        for ch in self._channels.values():
            ch.close()

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass
