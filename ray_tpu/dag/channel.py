"""Compiled-graph channels: single-writer single-reader shm mailboxes.

Reference analog: ``python/ray/experimental/channel/shared_memory_channel.py``
backed by C++ mutable plasma objects (``experimental_mutable_object_manager.h:44``
— versioned, reader/writer-synced shm buffers). Same design, serverless: a
POSIX shm segment holding {write_seq, read_seq, stop, payload}; the writer
blocks until the previous value is consumed (1-slot backpressure — exactly
the per-edge buffering a pipeline-parallel microbatch loop needs), the reader
blocks until a new version is published. Values too big for the segment
spill to the object store and the channel carries the ObjectRef.
"""
from __future__ import annotations

import logging
import pickle
import struct
import time
from multiprocessing import resource_tracker, shared_memory
from typing import Any, List, Optional

logger = logging.getLogger(__name__)

_MAGIC = 0x52544348  # "RTCH"
_HDR = struct.Struct("<IIQQQBB6x")  # magic, cap, wseq, rseq, nbytes, kind, stop
_FRAME_COUNT = struct.Struct("<I")
_FRAME_LEN = struct.Struct("<Q")
KIND_INLINE = 0
KIND_REF = 1

DEFAULT_CAPACITY = 1 << 20


class ChannelTimeoutError(TimeoutError):
    pass


class ChannelClosedError(RuntimeError):
    pass


def _pack_frames(frames: List[bytes]) -> bytes:
    out = bytearray()
    out += _FRAME_COUNT.pack(len(frames))
    for f in frames:
        out += _FRAME_LEN.pack(len(f))
    for f in frames:
        out += bytes(f)
    return bytes(out)


def _unpack_frames(buf: memoryview) -> List[bytes]:
    n = _FRAME_COUNT.unpack_from(buf, 0)[0]
    pos = _FRAME_COUNT.size
    lens = []
    for _ in range(n):
        lens.append(_FRAME_LEN.unpack_from(buf, pos)[0])
        pos += _FRAME_LEN.size
    frames = []
    for ln in lens:
        # copy: the segment is overwritten by the next write
        frames.append(bytes(buf[pos:pos + ln]))
        pos += ln
    return frames


class Channel:
    """One direction, one writer process, one reader process."""

    def __init__(self, name: str, capacity: int = DEFAULT_CAPACITY,
                 create: bool = False):
        self.name = name
        if create:
            self._shm = shared_memory.SharedMemory(
                name=name, create=True, size=capacity + _HDR.size
            )
            _HDR.pack_into(self._shm.buf, 0, _MAGIC, capacity, 0, 0, 0, 0, 0)
        else:
            self._shm = shared_memory.SharedMemory(name=name, create=False)
        try:
            resource_tracker.unregister(self._shm._name, "shared_memory")  # noqa: SLF001
        except Exception:
            pass
        self.capacity = _HDR.unpack_from(self._shm.buf, 0)[1]
        self.created = create
        # Spilled-value refs pinned by the WRITER until the reader consumes
        # that seq: the ObjectRef inside the channel is just bytes — without
        # this, the only live ref dies when write() returns and the store
        # frees the object before the reader can get() it.
        self._spills: List[tuple] = []

    # -- raw header ops ------------------------------------------------------
    # Fields are written individually: writer owns {wseq, nbytes, kind},
    # reader owns {rseq}, the tearing-down driver owns {stop}. No op may
    # rewrite another owner's field or a concurrent update would be lost
    # (e.g. a mid-write actor clobbering the stop flag during teardown).

    _OFF_WSEQ, _OFF_RSEQ, _OFF_NBYTES, _OFF_KIND, _OFF_STOP = 8, 16, 24, 32, 33
    _U64 = struct.Struct("<Q")

    def _hdr(self):
        return _HDR.unpack_from(self._shm.buf, 0)

    def set_stop(self):
        self._shm.buf[self._OFF_STOP] = 1

    @property
    def stopped(self) -> bool:
        return self._shm.buf[self._OFF_STOP] == 1

    def _wait(self, cond, timeout: Optional[float]):
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 0.0001
        while True:
            hdr = self._hdr()
            if hdr[6]:
                raise ChannelClosedError(f"channel {self.name} torn down")
            if cond(hdr):
                return hdr
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeoutError(f"channel {self.name} wait timed out")
            time.sleep(delay)
            # Back off to a deep sleep: a driver-side spin at sub-ms cadence
            # can starve the SAME process's event-loop thread (the in-process
            # head) of the GIL on small hosts — observed as worker→head RPCs
            # stalling for exactly as long as the spin runs.
            delay = min(delay * 2, 0.02)

    # -- value ops -----------------------------------------------------------

    def write(self, value: Any, ctx=None, timeout: Optional[float] = None):
        """Serialize and publish; blocks while the previous value is
        unconsumed (backpressure)."""
        if ctx is None:
            from ray_tpu._private.worker import get_global_worker

            ctx = get_global_worker().ctx
        sobj = ctx.serialize(value)
        frames = sobj.to_frames()
        kind = KIND_INLINE
        ref = None
        total = sum(len(f) for f in frames)
        overhead = _FRAME_COUNT.size + _FRAME_LEN.size * len(frames)
        if total + overhead <= self.capacity:
            blob = _pack_frames(frames)
        else:
            # Spill the already-serialized frames (no second serialization)
            # and carry the ref. Channel values must not contain nested
            # ObjectRefs (no borrow registration on this path).
            from ray_tpu._private.worker import get_global_worker

            ref = get_global_worker().put_serialized(
                [bytes(f) for f in frames], total
            )
            blob = pickle.dumps(ref)
            kind = KIND_REF
            if len(blob) > self.capacity:
                raise ValueError("spilled ref larger than channel capacity")
        hdr = self._wait(lambda h: h[2] == h[3], timeout)  # consumed
        w = hdr[2]
        # this wait proves seqs <= w are consumed: drop their spill pins
        self._spills = [(sq, r) for sq, r in self._spills if sq > w]
        self._shm.buf[_HDR.size:_HDR.size + len(blob)] = blob
        # publish LAST: nbytes/kind first, then the seq bump readers spin on
        self._U64.pack_into(self._shm.buf, self._OFF_NBYTES, len(blob))
        self._shm.buf[self._OFF_KIND] = kind
        self._U64.pack_into(self._shm.buf, self._OFF_WSEQ, w + 1)
        if ref is not None:
            self._spills.append((w + 1, ref))

    def read(self, ctx=None, timeout: Optional[float] = None) -> Any:
        """Block for the next value, consume it, return it."""
        if ctx is None:
            from ray_tpu._private.worker import get_global_worker

            ctx = get_global_worker().ctx
        hdr = self._wait(lambda h: h[2] > h[3], timeout)  # unread value
        w, nbytes, kind = hdr[2], hdr[4], hdr[5]
        buf = memoryview(self._shm.buf)[_HDR.size:_HDR.size + nbytes]
        if kind == KIND_REF:
            import ray_tpu

            ref = pickle.loads(bytes(buf))
            value = ray_tpu.get(ref)
        else:
            frames = _unpack_frames(buf)
            value = ctx.deserialize_frames(frames)
        del buf
        self._U64.pack_into(self._shm.buf, self._OFF_RSEQ, w)  # consume
        return value

    def close(self):
        try:
            self.set_stop()
        except Exception:
            pass
        self._spills.clear()
        if self.created:
            from ray_tpu._private.object_store import _safe_unlink

            try:
                # re-register + unlink keeps the resource_tracker's books
                # balanced (we unregistered at create; unlink unregisters
                # again — unbalanced, its process logs KeyErrors at exit)
                _safe_unlink(self._shm)
            except FileNotFoundError:
                pass
            except Exception:
                pass
        # keep the mapping (readers may be mid-read); dies with the process


class DeviceChannel:
    """Accelerator-array channel: the 1-slot mailbox carries descriptors;
    array payloads ride the object store as RAW buffers — the shm arena on
    one machine, the native C++ xfer plane (DCN) across hosts — and land
    with ``jax.device_put`` on the reader's default device.

    Reference analog (behavior, not code):
    ``python/ray/experimental/channel/torch_tensor_accelerator_channel.py``
    + ``communicator.py:18`` — tensor-carrying channels selected by type
    hint (``with_tensor_transport()``), transported out-of-band (NCCL
    there; arena/xfer here — TPU DCN transfers are host-mediated, there is
    no NCCL peer plane) while the control message stays tiny. Array bytes
    are never pickled; non-array pytree leaves ride inline.
    """

    def __init__(self, name: str, capacity: int = DEFAULT_CAPACITY,
                 create: bool = False):
        # The mailbox carries descriptors + non-array pytree leaves; the
        # configured capacity is honored so big non-array leaves keep their
        # inline headroom (array payloads always ride the object store).
        self._ctl = Channel(name, capacity=capacity, create=create)
        self.name = name
        self.created = create
        # Writer-side record of the newest payload: freed at close if the
        # reader never consumed it. Consumed payloads are freed by the
        # READER after the fetch — the mailbox-consumed signal fires before
        # the payload fetch, so a writer-side free would race it.
        self._last_oid: Optional[str] = None

    # channel-protocol surface used by the exec loops / teardown
    def set_stop(self):
        self._ctl.set_stop()

    @property
    def stopped(self) -> bool:
        return self._ctl.stopped

    def write(self, value: Any, ctx=None, timeout: Optional[float] = None):
        import jax
        import numpy as np

        from ray_tpu._private.worker import get_global_worker

        w = get_global_worker()
        leaves, treedef = jax.tree_util.tree_flatten(value)
        descs = []
        frames: List[Any] = []
        others: List[Any] = []
        for leaf in leaves:
            if isinstance(leaf, jax.Array) or isinstance(leaf, np.ndarray):
                host = np.asarray(leaf)  # device→host
                # shape recorded BEFORE ascontiguousarray: it promotes 0-d
                # scalars to shape (1,), which must not leak to the reader
                shape = host.shape
                arr = np.ascontiguousarray(host)
                descs.append((str(arr.dtype), shape))
                # byte-format view: the store copies via memoryview slices
                frames.append(memoryview(arr).cast("B"))
            else:
                descs.append(None)
                others.append(leaf)
        oid = None
        meta = None
        if frames:
            # transient: readers device_put a copy, so frees fully unmap
            oid, meta = w.put_raw_frames(frames, transient=True)
        try:
            self._ctl.write(
                {"descs": descs, "tree": treedef, "others": others,
                 "oid": oid, "meta": meta,
                 "addr": list(w.addr) if w.addr else None},
                ctx=ctx, timeout=timeout,
            )
        except BaseException:
            # Never published: nobody will ever consume (and free) it.
            if oid is not None:
                try:
                    w.shm.free(oid)
                    w.gcs.notify("object_free", {"oids": [oid]})
                except Exception as e:
                    logger.debug("channel write cleanup of %s failed: %s",
                                 oid, e)
            raise
        self._last_oid = oid

    def read(self, ctx=None, timeout: Optional[float] = None) -> Any:
        import jax
        import numpy as np

        from ray_tpu._private.worker import get_global_worker

        w = get_global_worker()
        msg = self._ctl.read(ctx=ctx, timeout=timeout)
        arrays = []
        if msg["oid"] is not None:
            raw = w.shm.get_frames(msg["oid"], msg["meta"])
            if raw is None:
                # other host: bulk-fetch through the native transfer plane
                raw = w.run_sync(
                    w._native_fetch(msg["oid"], msg["meta"])
                )
            if raw is None and msg.get("addr"):
                # native plane unavailable: pull the bytes from the writer
                # over RPC (slower, but the hint must not break the DAG)
                from ray_tpu._private.ids import ObjectID
                from ray_tpu.object_ref import ObjectRef

                try:
                    entry = w.run_sync(w._pull_from_owner(
                        ObjectRef(ObjectID.from_hex(msg["oid"]), None),
                        None, inline=True, addr=tuple(msg["addr"]),
                    ))
                    if entry[0] == "mem":
                        raw = entry[1]
                except Exception:
                    raw = None
            if raw is None:
                raise ChannelClosedError(
                    f"device payload {msg['oid'][:12]} unavailable"
                )
            host = [
                np.frombuffer(buf, dtype=np.dtype(dt)).reshape(shape)
                for buf, (dt, shape) in zip(
                    raw, [d for d in msg["descs"] if d is not None]
                )
            ]
            # one transfer call for all leaves; lands on the default device
            arrays = jax.device_put(host)
        out_leaves = []
        ai = oi = 0
        for d in msg["descs"]:
            if d is None:
                out_leaves.append(msg["others"][oi])
                oi += 1
            else:
                out_leaves.append(arrays[ai])
                ai += 1
        if msg["oid"] is not None:
            # Reader owns the free: arrays are on-device now, every cached
            # copy (incl. the writer's arena block, via the object_free
            # fan-out) can go.
            try:
                w.gcs.notify("object_free", {"oids": [msg["oid"]]})
            except Exception as e:
                logger.debug("channel read free of %s failed: %s",
                             msg["oid"], e)
        return jax.tree_util.tree_unflatten(msg["tree"], out_leaves)

    def close(self):
        if self._last_oid is not None:
            try:
                hdr = self._ctl._hdr()
                if hdr[2] > hdr[3]:  # final payload never consumed
                    from ray_tpu._private.worker import get_global_worker

                    get_global_worker().gcs.notify(
                        "object_free", {"oids": [self._last_oid]}
                    )
            except Exception as e:
                logger.debug("channel close free of %s failed: %s",
                             self._last_oid, e)
            self._last_oid = None
        self._ctl.close()
