"""Tombstone: durable workflows were removed from the reference
(``python/ray/workflow`` is a 4-line tombstone in Ray 2.55); kept here so the
import path fails with the same guidance."""

raise ImportError(
    "ray_tpu.workflow has been removed (matching the reference's removal of "
    "ray.workflow); compose tasks/actors or use the compiled graph API "
    "(ray_tpu.dag) instead."
)
