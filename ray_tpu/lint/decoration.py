"""Decoration-time linting: ``RAY_TPU_LINT=1`` makes ``@ray_tpu.remote``
raise :class:`~ray_tpu.exceptions.LintError` before a bad task ever ships.

Two layers, both cheap enough for import time:

* AST (Family A rules) over the decorated function/class source — the
  same rules the CLI runs, in ``assume_remote`` mode.
* Value-based checks that AST cannot do: the *actual* closure cells and
  referenced globals are probed against a non-picklable denylist, and the
  merged options dict (``.options()`` chains are dynamic) is validated.
"""
from __future__ import annotations

import dis
import inspect
import io
import os
import textwrap
import threading
from typing import List, Optional

from ray_tpu.lint.base import _SUPPRESS_RE, FAMILY_USER, Finding, lint_source
from ray_tpu.lint.user_rules import validate_options


def lint_enabled() -> bool:
    return os.environ.get("RAY_TPU_LINT") == "1"


def _nonpicklable_desc(value) -> Optional[str]:
    lock_types = (type(threading.Lock()), type(threading.RLock()))
    if isinstance(value, lock_types):
        return f"a {type(value).__name__}"
    if isinstance(value, (threading.Condition, threading.Event,
                          threading.Semaphore)):
        return f"a threading.{type(value).__name__}"
    if isinstance(value, io.IOBase):
        return "an open file handle"
    try:
        import socket
        if isinstance(value, socket.socket):
            return "a socket"
    except ImportError:  # pragma: no cover
        pass
    from ray_tpu.object_ref import ObjectRef
    if isinstance(value, ObjectRef):
        return "a live ObjectRef (pass it as an argument instead)"
    return None


def _ast_findings(target) -> List[Finding]:
    try:
        lines, start = inspect.getsourcelines(target)
        filename = inspect.getsourcefile(target) or "<unknown>"
    except (OSError, TypeError):
        return []  # REPL / dynamically generated code: no source, no AST
    source = textwrap.dedent("".join(lines))
    try:
        # RT104 is excluded here: the merged options dict is validated
        # value-side (covers dynamic .options() chains without
        # double-reporting constants visible in the decorator).
        findings = lint_source(
            source, filename, families=(FAMILY_USER,), assume_remote=True,
            select=("RT101", "RT102", "RT103"),
        )
    except SyntaxError:
        return []
    for f in findings:
        f.line += start - 1
    return findings


def _global_loads(code) -> set:
    """Names the code object actually loads as globals (recursing into
    nested code objects). co_names alone is wrong here: it also contains
    attribute names, so `x.lock` would false-positive against a module
    global named `lock`."""
    names = set()
    for ins in dis.get_instructions(code):
        if ins.opname == "LOAD_GLOBAL":
            names.add(ins.argval)
    for const in code.co_consts:
        if isinstance(const, type(code)):
            names |= _global_loads(const)
    return names


def _closure_findings(fn) -> List[Finding]:
    findings = []
    code = fn.__code__
    captured = {}
    if fn.__closure__:
        for name, cell in zip(code.co_freevars, fn.__closure__):
            try:
                captured[name] = cell.cell_contents
            except ValueError:
                continue  # empty cell (recursive def)
    for name in _global_loads(code):
        if name in fn.__globals__ and name not in captured:
            captured[name] = fn.__globals__[name]
    for name, value in captured.items():
        desc = _nonpicklable_desc(value)
        if desc is None:
            continue
        findings.append(Finding(
            "RT101",
            f"remote function '{fn.__name__}' captures {desc} ('{name}') "
            "from its defining scope; it cannot be pickled into the task "
            "spec",
            code.co_filename, code.co_firstlineno, 0,
        ))
    return findings


def _options_findings(target, options, where) -> List[Finding]:
    if not options:
        return []
    try:
        filename = inspect.getsourcefile(target) or "<unknown>"
        line = (target.__code__.co_firstlineno
                if hasattr(target, "__code__") else 1)
    except TypeError:
        filename, line = "<unknown>", 1
    return [Finding("RT104", msg, filename, line, 0)
            for msg in validate_options(options, where)]


def _suppressed_rules(target) -> set:
    """Rule ids suppressed anywhere in the target's source. Value-based
    findings (closure cells, merged options) have no single source line
    to anchor a comment to, so for them ``# raytpu: ignore[RULE]`` acts
    at function/class scope; a bare ``ignore`` returns {"*"}."""
    try:
        lines, _ = inspect.getsourcelines(target)
    except (OSError, TypeError):
        return set()
    rules: set = set()
    for line in lines:
        m = _SUPPRESS_RE.search(line)
        if m is None:
            continue
        spec = m.group("rules")
        if spec is None or not spec.strip():
            return {"*"}
        rules |= {r.strip() for r in spec.split(",")}
    return rules


def _filter_suppressed(findings: List[Finding], target) -> List[Finding]:
    suppressed = _suppressed_rules(target)
    if "*" in suppressed:
        return []
    return [f for f in findings if f.rule not in suppressed]


def _maybe_raise(findings: List[Finding]):
    if findings:
        from ray_tpu.exceptions import LintError

        raise LintError(findings)


def check_remote_function(fn, options: Optional[dict] = None):
    """Lint a function at ``@remote`` decoration time; raises LintError."""
    # AST findings honor line-level suppression inside lint_source; the
    # value-based probes have no comment-bearing line, so they honor
    # function-scope suppression instead.
    findings = _ast_findings(fn)
    value_findings = _closure_findings(fn)
    value_findings.extend(_options_findings(
        fn, options, f"@remote on '{fn.__name__}'"
    ))
    findings.extend(_filter_suppressed(value_findings, fn))
    _maybe_raise(findings)


def check_actor_class(cls, options: Optional[dict] = None):
    """Lint an actor class at ``@remote`` decoration time; raises LintError."""
    findings = _ast_findings(cls)
    value_findings = []
    for name, member in vars(cls).items():
        if inspect.isfunction(member):
            value_findings.extend(_closure_findings(member))
    value_findings.extend(_options_findings(
        cls, options, f"@remote on '{cls.__name__}'"
    ))
    findings.extend(_filter_suppressed(value_findings, cls))
    _maybe_raise(findings)
