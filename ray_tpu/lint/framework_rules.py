"""Family B — self-analysis of the framework's own hot paths.

RT201  blocking call while a threading.Lock/RLock is held
RT202  lock-acquisition-order inversion (or non-reentrant re-acquire)
RT203  silently swallowed exception on an RPC/reply path
RT204  constant time.sleep() in a retry/poll loop (use _private.backoff)

These run over ``ray_tpu/_private/`` (and any path passed with
``--framework``). The lock heuristics are name-based: any with-item whose
terminal identifier contains "lock" counts as a lock — that matches every
lock in the codebase (``self._lock``, ``self._plock``, ``peer_lock``,
``_cwd_lock``...) without needing type inference.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.lint.base import (
    FAMILY_FRAMEWORK,
    Finding,
    ModuleContext,
    dotted,
    register,
    terminal_name,
)

# Dotted call targets that block the calling thread.
_BLOCKING_DOTTED = {
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "select.select", "os.waitpid",
}
# Method names that block regardless of receiver: socket I/O and
# subprocess handshakes. Chosen to be unambiguous in this codebase
# (generic names like .send/.get/.join are excluded on purpose).
_BLOCKING_ATTRS = {
    "recv", "recvfrom", "recv_into", "accept", "sendall", "communicate",
}


def _is_lock_expr(node: ast.AST) -> bool:
    name = terminal_name(node)
    return name is not None and "lock" in name.lower()


def _lock_names(with_node) -> List[Tuple[str, ast.AST]]:
    out = []
    for item in with_node.items:
        expr = item.context_expr
        if _is_lock_expr(expr):
            out.append((dotted(expr) or terminal_name(expr), expr))
    return out


def _is_blocking_call(ctx: ModuleContext, call: ast.Call) -> Optional[str]:
    if ctx.is_time_sleep(call):
        return "time.sleep()"
    name = dotted(call.func)
    if name in _BLOCKING_DOTTED:
        return f"{name}()"
    if isinstance(call.func, ast.Attribute):
        if call.func.attr in _BLOCKING_ATTRS:
            return f".{call.func.attr}()"
        # Future.result() with no deadline blocks indefinitely.
        if call.func.attr == "result" and not call.args and not call.keywords:
            return ".result()"
    return None


class _LockWalker(ast.NodeVisitor):
    """Tracks the stack of held locks per function, emitting RT201
    findings and RT202 acquisition-order edges."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.findings: List[Finding] = []
        # (class_name, outer_lock, inner_lock) -> first location
        self.edges: Dict[Tuple[Optional[str], str, str],
                         Tuple[int, int]] = {}
        self._held: List[str] = []
        self._class: Optional[str] = None

    def visit_ClassDef(self, node):
        prev, self._class = self._class, node.name
        self.generic_visit(node)
        self._class = prev

    def _visit_fn(self, node):
        # A nested def under a lock runs later, not under the lock.
        held, self._held = self._held, []
        self.generic_visit(node)
        self._held = held

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def _visit_with(self, node, is_async):
        locks = _lock_names(node)
        for name, expr in locks:
            for outer in self._held:
                if outer == name:
                    self.findings.append(Finding(
                        "RT202",
                        f"lock '{name}' re-acquired while already held — "
                        "deadlock if it is a non-reentrant threading.Lock",
                        self.ctx.filename, expr.lineno, expr.col_offset,
                    ))
                else:
                    self.edges.setdefault(
                        (self._class, outer, name),
                        (expr.lineno, expr.col_offset),
                    )
        # RT201 applies while any lock is held — async locks park only the
        # coroutine, but a sync blocking call inside an async-with still
        # stalls the whole event loop, so those are flagged too.
        self._held.extend(name for name, _ in locks)
        for stmt in node.body:
            self.visit(stmt)
        if locks:
            del self._held[len(self._held) - len(locks):]

    def visit_With(self, node):
        self._visit_with(node, is_async=False)

    def visit_AsyncWith(self, node):
        self._visit_with(node, is_async=True)

    def visit_Call(self, node):
        if self._held:
            desc = _is_blocking_call(self.ctx, node)
            if desc is not None:
                self.findings.append(Finding(
                    "RT201",
                    f"blocking {desc} while holding lock "
                    f"'{self._held[-1]}' — every thread contending on the "
                    "lock stalls for the full call; move the call outside "
                    "the critical section or snapshot state under the "
                    "lock and operate on the copy",
                    self.ctx.filename, node.lineno, node.col_offset,
                ))
        self.generic_visit(node)


def _lock_walker(ctx: ModuleContext) -> _LockWalker:
    """One traversal shared by RT201/RT202 (cached per module)."""
    walker = getattr(ctx, "_lock_walker", None)
    if walker is None:
        walker = _LockWalker(ctx)
        walker.visit(ctx.tree)
        ctx._lock_walker = walker
    return walker


@register("RT201", FAMILY_FRAMEWORK,
          "blocking call while holding a lock")
def check_blocking_under_lock(ctx: ModuleContext) -> List[Finding]:
    walker = _lock_walker(ctx)
    return [f for f in walker.findings if f.rule == "RT201"]


@register("RT202", FAMILY_FRAMEWORK,
          "lock-acquisition-order inversion across the module")
def check_lock_order(ctx: ModuleContext) -> List[Finding]:
    walker = _lock_walker(ctx)
    findings = [f for f in walker.findings if f.rule == "RT202"]
    reported: Set[frozenset] = set()
    for (cls, outer, inner), (line, col) in walker.edges.items():
        rev = walker.edges.get((cls, inner, outer))
        if rev is None:
            continue
        pair = frozenset(((cls, outer), (cls, inner)))
        if pair in reported:
            continue
        reported.add(pair)
        where = f"class {cls}" if cls else "module"
        findings.append(Finding(
            "RT202",
            f"lock-order inversion in {where}: '{outer}' -> '{inner}' "
            f"here but '{inner}' -> '{outer}' at line {rev[0]} — two "
            "threads taking the two paths concurrently deadlock; pick "
            "one order and enforce it",
            ctx.filename, line, col,
        ))
    return findings


_RPC_EXC_NAMES = {"RpcError", "ConnectionLost"}
_RPC_CALL_ATTRS = {"call", "notify"}


def _handler_types(handler: ast.ExceptHandler) -> Set[str]:
    t = handler.type
    out: Set[str] = set()
    if t is None:
        return out
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in elts:
        name = terminal_name(e)
        if name:
            out.add(name)
    return out


def _try_has_rpc_call(try_node: ast.Try) -> bool:
    for stmt in try_node.body:
        for node in ast.walk(stmt):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _RPC_CALL_ATTRS):
                return True
    return False


@register("RT203", FAMILY_FRAMEWORK,
          "silently swallowed exception on an RPC/reply path")
def check_silent_swallow(ctx: ModuleContext) -> List[Finding]:
    findings = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Try):
            continue
        for handler in node.handlers:
            if not (len(handler.body) == 1
                    and isinstance(handler.body[0], ast.Pass)):
                continue
            caught = _handler_types(handler)
            rpc_exc = caught & _RPC_EXC_NAMES
            if rpc_exc or ((not caught or "Exception" in caught)
                           and _try_has_rpc_call(node)):
                what = "/".join(sorted(rpc_exc)) if rpc_exc else "Exception"
                findings.append(Finding(
                    "RT203",
                    f"'except {what}: pass' swallows an RPC-path failure "
                    "with no trace — at minimum logger.debug() it so a "
                    "dropped reply is diagnosable from logs",
                    ctx.filename, handler.lineno, handler.col_offset,
                ))
    return findings


@register("RT204", FAMILY_FRAMEWORK,
          "constant time.sleep() in a retry/poll loop")
def check_constant_sleep_loop(ctx: ModuleContext) -> List[Finding]:
    findings = []
    seen = set()

    def scan(node, loop_line):
        # Don't descend into nested defs (deferred execution) or nested
        # loops (they report against their own line).
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.While)):
            return
        if (isinstance(node, ast.Call) and ctx.is_time_sleep(node)
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, (int, float))
                and (node.lineno, node.col_offset) not in seen):
            seen.add((node.lineno, node.col_offset))
            findings.append(Finding(
                "RT204",
                f"constant time.sleep({node.args[0].value}) inside the "
                f"loop at line {loop_line}: fixed-period retries "
                "synchronize contenders and thundering-herd the head — "
                "use ray_tpu._private.backoff.Backoff (jittered, capped) "
                "instead",
                ctx.filename, node.lineno, node.col_offset,
            ))
        for child in ast.iter_child_nodes(node):
            scan(child, loop_line)

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.While):
            for stmt in node.body:
                scan(stmt, node.lineno)
    return findings
