"""Shared lint infrastructure: findings, rule registry, suppression.

``ray_tpu.lint`` is an AST-based distributed-correctness analyzer. The
reference engine (Ray, Moritz et al., OSDI'18) catches these failure
classes — non-serializable closures, blocked-worker deadlocks, leaked
borrows, unplaceable resource shapes — only at runtime, deep inside a
cluster; its own task-spec validation and ownership bookkeeping show the
invariants are statically checkable at ``@remote`` decoration time.

Four rule families:

* **Family A (user code)** — rules that fire on functions/classes passed
  to ``@ray_tpu.remote``: ``RT101``-``RT104``.
* **Family B (framework self-analysis)** — rules that keep
  ``ray_tpu/_private/`` honest about its own thread+lock discipline:
  ``RT201``-``RT204``.
* **Family C (concurrency)** — asyncio/thread hazards in framework
  code (blocking the core loop, touching a loop from the wrong thread,
  fire-and-forget tasks): ``RT301``-``RT305``.
* **Family D (protocol invariants)** — project-scope drift checks
  between the code and the pinned ``lint/catalog.py`` (wire flags,
  config gates, faultpoints, taskpath phases): ``RT401``-``RT404``.
  These run over the whole scanned file set at once (a receiver branch
  in one module satisfies a sender in another), so they activate for
  directory scans and explicit ``--select RT4`` runs.

Suppression: append ``# raytpu: ignore[RT201]`` (comma-separated ids, or
bare ``# raytpu: ignore`` for all rules) to the flagged line.
"""
from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Callable, Dict, List, Optional, Sequence

FAMILY_USER = "A"
FAMILY_FRAMEWORK = "B"
FAMILY_CONCURRENCY = "C"
FAMILY_PROTOCOL = "D"

#: Families whose rules run per-module (Family D runs per-project).
MODULE_FAMILIES = (FAMILY_USER, FAMILY_FRAMEWORK, FAMILY_CONCURRENCY)

_SUPPRESS_RE = re.compile(
    r"#\s*raytpu:\s*ignore(?:\[(?P<rules>[A-Za-z0-9_,\s]*)\])?"
)


_FAMILY_BY_PREFIX = {"RT1": "A", "RT2": "B", "RT3": "C", "RT4": "D"}


@dataclasses.dataclass
class Finding:
    rule: str
    message: str
    file: str
    line: int
    col: int

    @property
    def family(self) -> str:
        return _FAMILY_BY_PREFIX.get(self.rule[:3], "-")

    def format(self) -> str:
        return f"{self.file}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["family"] = self.family  # lets --json consumers filter by family
        return d


@dataclasses.dataclass
class Rule:
    rule_id: str
    family: str
    summary: str
    check: Callable[["ModuleContext"], List[Finding]]


#: rule id -> Rule. Populated by the ``@register`` decorators in
#: user_rules.py / framework_rules.py / concurrency_rules.py at import
#: time. Project-scope rules (Family D) live in ``PROJECT_RULES``.
RULES: Dict[str, Rule] = {}

#: rule id -> Rule whose check takes a :class:`ProjectContext` (all
#: scanned modules at once). Populated by invariant_rules.py.
PROJECT_RULES: Dict[str, Rule] = {}


def register(rule_id: str, family: str, summary: str):
    def deco(fn):
        RULES[rule_id] = Rule(rule_id, family, summary, fn)
        return fn

    return deco


def register_project(rule_id: str, family: str, summary: str):
    def deco(fn):
        PROJECT_RULES[rule_id] = Rule(rule_id, family, summary, fn)
        return fn

    return deco


def all_rules() -> Dict[str, Rule]:
    """Module + project rules in one registry view (populated)."""
    _load_rule_modules()
    merged = dict(RULES)
    merged.update(PROJECT_RULES)
    return merged


def _load_rule_modules():
    # Import for the registration side effect (idempotent).
    from ray_tpu.lint import (  # noqa: F401
        concurrency_rules,
        framework_rules,
        invariant_rules,
        user_rules,
    )


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` source text for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal_name(node: ast.AST) -> Optional[str]:
    """The last identifier of a Name/Attribute chain (``self._lock`` -> ``_lock``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


class ModuleContext:
    """One parsed module plus the import-alias facts rules need."""

    def __init__(self, source: str, filename: str = "<string>",
                 assume_remote: bool = False):
        self.source = source
        self.filename = filename
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename)
        #: decoration-time mode: the top-level def/class IS the remote
        #: target even though the decorator may be textually absent.
        self.assume_remote = assume_remote
        # Names bound to the ray_tpu module ("import ray_tpu as rt").
        self.ray_aliases = {"ray_tpu"}
        # Local name -> original ray_tpu attr ("from ray_tpu import get as g").
        self.from_ray = {}
        # Names bound to the time module / "from time import sleep".
        self.time_aliases = {"time"}
        self.from_time = {}
        self._scan_imports()

    def _scan_imports(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    bound = alias.asname or alias.name.split(".")[0]
                    if alias.name == "ray_tpu":
                        self.ray_aliases.add(bound)
                    elif alias.name == "time":
                        self.time_aliases.add(bound)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "ray_tpu":
                    for alias in node.names:
                        self.from_ray[alias.asname or alias.name] = alias.name
                elif node.module == "time":
                    for alias in node.names:
                        self.from_time[alias.asname or alias.name] = alias.name

    # ---------------------------------------------------------- matchers
    def is_ray_api_call(self, call: ast.Call, names: Sequence[str]) -> bool:
        """Does ``call`` invoke ``ray_tpu.<name>`` (via any alias or
        ``from ray_tpu import <name>``) for one of ``names``?"""
        fn = call.func
        if isinstance(fn, ast.Attribute) and fn.attr in names:
            base = fn.value
            return isinstance(base, ast.Name) and base.id in self.ray_aliases
        if isinstance(fn, ast.Name):
            return self.from_ray.get(fn.id) in names
        return False

    def is_time_sleep(self, call: ast.Call) -> bool:
        fn = call.func
        if isinstance(fn, ast.Attribute) and fn.attr == "sleep":
            base = fn.value
            return isinstance(base, ast.Name) and base.id in self.time_aliases
        if isinstance(fn, ast.Name):
            return self.from_time.get(fn.id) == "sleep"
        return False

    def is_remote_decorated(self, node: ast.AST) -> bool:
        """Is this def/class decorated with ``@remote`` / ``@ray_tpu.remote``
        (optionally called with options)?"""
        for dec in getattr(node, "decorator_list", []):
            target = dec.func if isinstance(dec, ast.Call) else dec
            if isinstance(target, ast.Attribute) and target.attr == "remote":
                base = target.value
                if isinstance(base, ast.Name) and base.id in self.ray_aliases:
                    return True
            elif isinstance(target, ast.Name):
                if self.from_ray.get(target.id) == "remote":
                    return True
        return False

    # ------------------------------------------------------- suppression
    def suppressed(self, finding: Finding) -> bool:
        if not 1 <= finding.line <= len(self.lines):
            return False
        m = _SUPPRESS_RE.search(self.lines[finding.line - 1])
        if m is None:
            return False
        rules = m.group("rules")
        if rules is None or not rules.strip():
            return True  # bare "# raytpu: ignore"
        return finding.rule in {r.strip() for r in rules.split(",")}


class ProjectContext:
    """Every parsed module of one lint invocation, for project-scope
    (Family D) rules: a wire flag packed in ``worker.py`` is satisfied
    by its receiver branch in ``protocol.py``.

    ``complete`` marks a scan that covered a whole directory tree —
    only then may rules report *absence* findings (a catalog entry with
    no site anywhere); partial scans (single files, fixture tests) only
    report asymmetries among the sites they can see.
    """

    def __init__(self, modules: Sequence[ModuleContext],
                 complete: bool = False):
        self.modules = list(modules)
        self.complete = complete
        self._by_file = {m.filename: m for m in self.modules}

    def suppressed(self, finding: Finding) -> bool:
        ctx = self._by_file.get(finding.file)
        return ctx.suppressed(finding) if ctx is not None else False


def lint_source(source: str, filename: str = "<string>",
                families: Sequence[str] = (FAMILY_USER, FAMILY_FRAMEWORK),
                assume_remote: bool = False,
                select: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the per-module registry against one module's source.
    ``select`` filters by rule-id prefix (``["RT2"]`` -> Family B only).
    Family D (project scope) runs through :func:`lint_paths` /
    :func:`lint_project` instead."""
    _load_rule_modules()
    ctx = ModuleContext(source, filename, assume_remote=assume_remote)
    findings: List[Finding] = []
    for rule in RULES.values():
        if rule.family not in families:
            continue
        if select and not any(rule.rule_id.startswith(s) for s in select):
            continue
        findings.extend(rule.check(ctx))
    findings = [f for f in findings if not ctx.suppressed(f)]
    findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return findings


def lint_project(modules: Sequence[ModuleContext], complete: bool = False,
                 select: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the project-scope (Family D) rules over parsed modules."""
    _load_rule_modules()
    pctx = ProjectContext(modules, complete=complete)
    findings: List[Finding] = []
    for rule in PROJECT_RULES.values():
        if select and not any(rule.rule_id.startswith(s) for s in select):
            continue
        findings.extend(rule.check(pctx))
    findings = [f for f in findings if not pctx.suppressed(f)]
    findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return findings


def _is_framework_path(path: str) -> bool:
    # Framework self-analysis trees: the core runtime AND the serve plane
    # (its router/controller/proxies hold locks and swallow RPC failures
    # exactly the way Family B exists to catch). "serve" alone would also
    # match user dirs named serve/, so require it DIRECTLY under a
    # ray_tpu parent segment.
    parts = os.path.normpath(path).split(os.sep)
    if "_private" in parts:
        return True
    return any(
        a == "ray_tpu" and b == "serve" for a, b in zip(parts, parts[1:])
    )


def _is_lint_path(path: str) -> bool:
    # The analyzer's own package: rule modules and the catalog are full
    # of wire-flag / faultpoint string fixtures that would scan as fake
    # pack/fire sites. Module rules still run; the project pass skips it.
    parts = os.path.normpath(path).split(os.sep)
    return any(
        a == "ray_tpu" and b == "lint" for a, b in zip(parts, parts[1:])
    )


def lint_file(path: str, framework: Optional[bool] = None,
              select: Optional[Sequence[str]] = None,
              collect: Optional[List[ModuleContext]] = None
              ) -> List[Finding]:
    """Lint one file with the per-module families. Family A always runs;
    Families B and C run for files under ``_private/`` (framework
    self-analysis) or when ``framework=True``. A parsed
    :class:`ModuleContext` is appended to ``collect`` for the caller's
    project pass."""
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    run_b = framework if framework is not None else _is_framework_path(path)
    families = MODULE_FAMILIES if run_b else (FAMILY_USER,)
    try:
        findings = lint_source(source, path, families=families,
                               select=select)
    except SyntaxError as exc:
        return [Finding("RT000", f"syntax error: {exc.msg}", path,
                        exc.lineno or 1, exc.offset or 0)]
    if collect is not None and not _is_lint_path(path):
        collect.append(ModuleContext(source, path))
    return findings


def _want_project_rules(select: Optional[Sequence[str]],
                        scanned_dir: bool, framework: Optional[bool],
                        modules: Sequence[ModuleContext]) -> bool:
    # Family D needs cross-module visibility to mean anything, so by
    # default it rides directory scans that include framework code;
    # ``--select RT4...`` opts a partial (single-file / fixture) scan in
    # explicitly.
    if select:
        return any(s == "RT" or s.startswith("RT4") for s in select)
    if not scanned_dir:
        return False
    return framework is True or any(
        _is_framework_path(m.filename) for m in modules
    )


def lint_paths(paths: Sequence[str], framework: Optional[bool] = None,
               select: Optional[Sequence[str]] = None) -> List[Finding]:
    findings: List[Finding] = []
    modules: List[ModuleContext] = []
    scanned_dir = False
    for path in paths:
        if os.path.isdir(path):
            scanned_dir = True
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git", "node_modules")
                )
                for name in sorted(files):
                    if name.endswith(".py"):
                        findings.extend(lint_file(
                            os.path.join(root, name), framework, select,
                            collect=modules,
                        ))
        else:
            findings.extend(lint_file(path, framework, select,
                                      collect=modules))
    if modules and _want_project_rules(select, scanned_dir, framework,
                                       modules):
        findings.extend(lint_project(modules, complete=scanned_dir,
                                     select=select))
    return findings
