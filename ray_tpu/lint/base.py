"""Shared lint infrastructure: findings, rule registry, suppression.

``ray_tpu.lint`` is an AST-based distributed-correctness analyzer. The
reference engine (Ray, Moritz et al., OSDI'18) catches these failure
classes — non-serializable closures, blocked-worker deadlocks, leaked
borrows, unplaceable resource shapes — only at runtime, deep inside a
cluster; its own task-spec validation and ownership bookkeeping show the
invariants are statically checkable at ``@remote`` decoration time.

Two rule families:

* **Family A (user code)** — rules that fire on functions/classes passed
  to ``@ray_tpu.remote``: ``RT101``-``RT104``.
* **Family B (framework self-analysis)** — rules that keep
  ``ray_tpu/_private/`` honest about its own thread+lock discipline:
  ``RT201``-``RT204``.

Suppression: append ``# raytpu: ignore[RT201]`` (comma-separated ids, or
bare ``# raytpu: ignore`` for all rules) to the flagged line.
"""
from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Callable, Dict, List, Optional, Sequence

FAMILY_USER = "A"
FAMILY_FRAMEWORK = "B"

_SUPPRESS_RE = re.compile(
    r"#\s*raytpu:\s*ignore(?:\[(?P<rules>[A-Za-z0-9_,\s]*)\])?"
)


@dataclasses.dataclass
class Finding:
    rule: str
    message: str
    file: str
    line: int
    col: int

    def format(self) -> str:
        return f"{self.file}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Rule:
    rule_id: str
    family: str
    summary: str
    check: Callable[["ModuleContext"], List[Finding]]


#: rule id -> Rule. Populated by the ``@register`` decorators in
#: user_rules.py / framework_rules.py at import time.
RULES: Dict[str, Rule] = {}


def register(rule_id: str, family: str, summary: str):
    def deco(fn):
        RULES[rule_id] = Rule(rule_id, family, summary, fn)
        return fn

    return deco


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` source text for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal_name(node: ast.AST) -> Optional[str]:
    """The last identifier of a Name/Attribute chain (``self._lock`` -> ``_lock``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


class ModuleContext:
    """One parsed module plus the import-alias facts rules need."""

    def __init__(self, source: str, filename: str = "<string>",
                 assume_remote: bool = False):
        self.source = source
        self.filename = filename
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename)
        #: decoration-time mode: the top-level def/class IS the remote
        #: target even though the decorator may be textually absent.
        self.assume_remote = assume_remote
        # Names bound to the ray_tpu module ("import ray_tpu as rt").
        self.ray_aliases = {"ray_tpu"}
        # Local name -> original ray_tpu attr ("from ray_tpu import get as g").
        self.from_ray = {}
        # Names bound to the time module / "from time import sleep".
        self.time_aliases = {"time"}
        self.from_time = {}
        self._scan_imports()

    def _scan_imports(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    bound = alias.asname or alias.name.split(".")[0]
                    if alias.name == "ray_tpu":
                        self.ray_aliases.add(bound)
                    elif alias.name == "time":
                        self.time_aliases.add(bound)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "ray_tpu":
                    for alias in node.names:
                        self.from_ray[alias.asname or alias.name] = alias.name
                elif node.module == "time":
                    for alias in node.names:
                        self.from_time[alias.asname or alias.name] = alias.name

    # ---------------------------------------------------------- matchers
    def is_ray_api_call(self, call: ast.Call, names: Sequence[str]) -> bool:
        """Does ``call`` invoke ``ray_tpu.<name>`` (via any alias or
        ``from ray_tpu import <name>``) for one of ``names``?"""
        fn = call.func
        if isinstance(fn, ast.Attribute) and fn.attr in names:
            base = fn.value
            return isinstance(base, ast.Name) and base.id in self.ray_aliases
        if isinstance(fn, ast.Name):
            return self.from_ray.get(fn.id) in names
        return False

    def is_time_sleep(self, call: ast.Call) -> bool:
        fn = call.func
        if isinstance(fn, ast.Attribute) and fn.attr == "sleep":
            base = fn.value
            return isinstance(base, ast.Name) and base.id in self.time_aliases
        if isinstance(fn, ast.Name):
            return self.from_time.get(fn.id) == "sleep"
        return False

    def is_remote_decorated(self, node: ast.AST) -> bool:
        """Is this def/class decorated with ``@remote`` / ``@ray_tpu.remote``
        (optionally called with options)?"""
        for dec in getattr(node, "decorator_list", []):
            target = dec.func if isinstance(dec, ast.Call) else dec
            if isinstance(target, ast.Attribute) and target.attr == "remote":
                base = target.value
                if isinstance(base, ast.Name) and base.id in self.ray_aliases:
                    return True
            elif isinstance(target, ast.Name):
                if self.from_ray.get(target.id) == "remote":
                    return True
        return False

    # ------------------------------------------------------- suppression
    def suppressed(self, finding: Finding) -> bool:
        if not 1 <= finding.line <= len(self.lines):
            return False
        m = _SUPPRESS_RE.search(self.lines[finding.line - 1])
        if m is None:
            return False
        rules = m.group("rules")
        if rules is None or not rules.strip():
            return True  # bare "# raytpu: ignore"
        return finding.rule in {r.strip() for r in rules.split(",")}


def lint_source(source: str, filename: str = "<string>",
                families: Sequence[str] = (FAMILY_USER, FAMILY_FRAMEWORK),
                assume_remote: bool = False,
                select: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the registry against one module's source. ``select`` filters by
    rule-id prefix (``["RT2"]`` -> Family B only)."""
    # Import for the registration side effect (idempotent).
    from ray_tpu.lint import framework_rules, user_rules  # noqa: F401

    ctx = ModuleContext(source, filename, assume_remote=assume_remote)
    findings: List[Finding] = []
    for rule in RULES.values():
        if rule.family not in families:
            continue
        if select and not any(rule.rule_id.startswith(s) for s in select):
            continue
        findings.extend(rule.check(ctx))
    findings = [f for f in findings if not ctx.suppressed(f)]
    findings.sort(key=lambda f: (f.file, f.line, f.col, f.rule))
    return findings


def _is_framework_path(path: str) -> bool:
    # Framework self-analysis trees: the core runtime AND the serve plane
    # (its router/controller/proxies hold locks and swallow RPC failures
    # exactly the way Family B exists to catch). "serve" alone would also
    # match user dirs named serve/, so require it DIRECTLY under a
    # ray_tpu parent segment.
    parts = os.path.normpath(path).split(os.sep)
    if "_private" in parts:
        return True
    return any(
        a == "ray_tpu" and b == "serve" for a, b in zip(parts, parts[1:])
    )


def lint_file(path: str, framework: Optional[bool] = None,
              select: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint one file. Family A always runs; Family B runs for files under
    ``_private/`` (framework self-analysis) or when ``framework=True``."""
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    run_b = framework if framework is not None else _is_framework_path(path)
    families = (FAMILY_USER, FAMILY_FRAMEWORK) if run_b else (FAMILY_USER,)
    try:
        return lint_source(source, path, families=families, select=select)
    except SyntaxError as exc:
        return [Finding("RT000", f"syntax error: {exc.msg}", path,
                        exc.lineno or 1, exc.offset or 0)]


def lint_paths(paths: Sequence[str], framework: Optional[bool] = None,
               select: Optional[Sequence[str]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for path in paths:
        if os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git", "node_modules")
                )
                for name in sorted(files):
                    if name.endswith(".py"):
                        findings.extend(lint_file(
                            os.path.join(root, name), framework, select
                        ))
        else:
            findings.extend(lint_file(path, framework, select))
    return findings
