"""Family D — protocol/config/chaos/phase invariants vs lint/catalog.py.

RT401  wire-flag asymmetry (packed without a receiver branch, consumed
       without a sender, or packed outside the pinned catalog)
RT402  config-gate drift (catalog vs rt_config declarations; a gate
       that is never read or never branched on is dead weight)
RT403  faultpoint drift (fire site not in the catalog; a cataloged
       point that is neither chaos-matrixed nor waived)
RT404  taskpath phase drift (span stage / phase label outside the
       pinned tables; catalog PHASES != taskpath.PHASES)

These are *project-scope* rules (``base.PROJECT_RULES``): a flag packed
in ``worker.py`` is satisfied by its receiver branch in ``protocol.py``,
so they run over every module of one lint invocation at once. Absence
findings (a catalog entry with no site anywhere) only fire on
``complete`` scans — a whole-directory pass — never on single-file or
fixture scans, which can only prove asymmetries among the sites they
can see.

Wire-site heuristics (kept deliberately name-based, like the Family B
lock rules): a *pack* is a short string key written into a dict bound
to a ``HEADER_VARS`` name (subscript store, ``setdefault``, or a dict
literal assigned to such a name / passed via a ``HEADER_KWARGS``
keyword); a *consume* is ``.get``/``.pop``/``in``/subscript-load on the
same names. Keys longer than 4 chars are verb-payload fields, not the
compact task-wire flag namespace, and stay out of scope.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.lint import catalog
from ray_tpu.lint.base import (
    FAMILY_PROTOCOL,
    Finding,
    ModuleContext,
    ProjectContext,
    _is_framework_path,
    dotted,
    register_project,
    terminal_name,
)

_SHORT_KEY_RE = re.compile(r"^_?[a-z][a-z0-9_]{0,3}$")

Site = Tuple[str, int, int]  # (file, line, col)


def _catalog_file() -> str:
    try:
        path = os.path.abspath(catalog.__file__)
        rel = os.path.relpath(path)
        return rel if not rel.startswith("..") else path
    except (AttributeError, ValueError):
        return "ray_tpu/lint/catalog.py"


def _absence(rule: str, message: str) -> Finding:
    """A finding with no code site (catalog entry matched nothing)."""
    return Finding(rule, message, _catalog_file(), 1, 0)


# ------------------------------------------------------------- wire scan

def _is_header_name(node: ast.AST) -> bool:
    t = terminal_name(node)
    return t is not None and t in catalog.HEADER_VARS


def _wire_sites(pctx: ProjectContext) -> Tuple[Dict[str, List[Site]],
                                               Dict[str, List[Site]]]:
    cached = getattr(pctx, "_wire_sites", None)
    if cached is not None:
        return cached
    packs: Dict[str, List[Site]] = {}
    consumes: Dict[str, List[Site]] = {}

    def pack(key, node, f):
        packs.setdefault(key, []).append((f, node.lineno, node.col_offset))

    def consume(key, node, f):
        consumes.setdefault(key, []).append(
            (f, node.lineno, node.col_offset))

    for mod in pctx.modules:
        # The task wire lives in the framework core; on complete scans
        # skip user-facing trees where short dict keys are unrelated.
        if pctx.complete and not _is_framework_path(mod.filename):
            continue
        f = mod.filename
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (isinstance(t, ast.Subscript)
                            and _is_header_name(t.value)
                            and isinstance(t.slice, ast.Constant)
                            and isinstance(t.slice.value, str)):
                        pack(t.slice.value, t, f)
                    elif (isinstance(t, (ast.Name, ast.Attribute))
                            and _is_header_name(t)
                            and isinstance(node.value, ast.Dict)):
                        for k in node.value.keys:
                            if (isinstance(k, ast.Constant)
                                    and isinstance(k.value, str)):
                                pack(k.value, k, f)
            elif isinstance(node, ast.Call):
                fn = node.func
                if (isinstance(fn, ast.Attribute)
                        and _is_header_name(fn.value) and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    key = node.args[0].value
                    if fn.attr == "setdefault":
                        pack(key, node, f)
                    elif fn.attr in ("get", "pop"):
                        consume(key, node, f)
                for kw in node.keywords:
                    if kw.arg in catalog.HEADER_KWARGS:
                        for sub in ast.walk(kw.value):
                            if isinstance(sub, ast.Dict):
                                for k in sub.keys:
                                    if (isinstance(k, ast.Constant)
                                            and isinstance(k.value, str)):
                                        pack(k.value, k, f)
            elif (isinstance(node, ast.Compare)
                    and isinstance(node.left, ast.Constant)
                    and isinstance(node.left.value, str)
                    and len(node.ops) == 1
                    and isinstance(node.ops[0], (ast.In, ast.NotIn))
                    and node.comparators
                    and _is_header_name(node.comparators[0])):
                consume(node.left.value, node, f)
            elif (isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, ast.Load)
                    and _is_header_name(node.value)
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)):
                consume(node.slice.value, node, f)
    pctx._wire_sites = (packs, consumes)
    return packs, consumes


@register_project("RT401", FAMILY_PROTOCOL,
                  "wire-flag asymmetry vs the pinned catalog")
def check_wire_flags(pctx: ProjectContext) -> List[Finding]:
    packs, consumes = _wire_sites(pctx)
    findings: List[Finding] = []
    for key, entry in catalog.WIRE_FLAGS.items():
        if entry.get("waive"):
            continue
        p, c = packs.get(key), consumes.get(key)
        if p and not c:
            f, line, col = p[0]
            findings.append(Finding(
                "RT401",
                f"wire flag '{key}' is packed here but no receiver "
                "branch consumes it in the scanned set — the bytes ride "
                "every frame for nothing, or the receiver silently "
                "ignores a behavior the sender thinks it negotiated; "
                "add the consume branch or retire the flag from "
                "lint/catalog.py WIRE_FLAGS",
                f, line, col,
            ))
        elif c and not p:
            f, line, col = c[0]
            findings.append(Finding(
                "RT401",
                f"wire flag '{key}' is consumed here but never packed "
                "by any sender in the scanned set — dead receiver "
                "branch, or the sender side lost the flag in a "
                "refactor; restore the pack site or retire the flag "
                "from lint/catalog.py WIRE_FLAGS",
                f, line, col,
            ))
        elif not p and not c and pctx.complete:
            findings.append(_absence(
                "RT401",
                f"cataloged wire flag '{key}' has no pack or consume "
                "site anywhere in the tree — stale catalog entry; "
                "remove it (or waive with a reason) in lint/catalog.py",
            ))
    known = set(catalog.WIRE_FLAGS) | set(catalog.WIRE_BASE)
    for key, sites in sorted(packs.items()):
        if key in known or not _SHORT_KEY_RE.match(key):
            continue
        f, line, col = sites[0]
        findings.append(Finding(
            "RT401",
            f"header key '{key}' is packed onto the wire but absent "
            "from lint/catalog.py (WIRE_FLAGS/WIRE_BASE) — every wire "
            "key must be pinned so senders and receivers cannot drift; "
            "catalog it with direction + description",
            f, line, col,
        ))
    return findings


# ------------------------------------------------------------- gate scan

def _rtconfig_aliases(mod: ModuleContext) -> Set[str]:
    names = {"rt_config"}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name == "rt_config" and alias.asname:
                    names.add(alias.asname)
    return names


_COERCIONS = {"bool", "int", "float", "str"}
_BRANCH_NODES = (ast.BoolOp, ast.UnaryOp, ast.Compare, ast.IfExp)


def _parents(mod: ModuleContext) -> Dict[int, ast.AST]:
    cached = getattr(mod, "_parent_map", None)
    if cached is None:
        cached = {}
        for node in ast.walk(mod.tree):
            for child in ast.iter_child_nodes(node):
                cached[id(child)] = node
        mod._parent_map = cached
    return cached


def _read_context(mod: ModuleContext, node: ast.AST) -> str:
    """'branch' | 'assign' | 'return' | 'other' for a gate read site."""
    parents = _parents(mod)
    cur = node
    while True:
        parent = parents.get(id(cur))
        if parent is None or isinstance(
                parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Module)):
            return "other"
        if isinstance(parent, (ast.If, ast.While)) and cur is parent.test:
            return "branch"
        if isinstance(parent, ast.Assert) and cur is parent.test:
            return "branch"
        if isinstance(parent, _BRANCH_NODES):
            return "branch"
        if isinstance(parent, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                               ast.NamedExpr)):
            return "assign"
        if isinstance(parent, ast.Return):
            return "return"
        if isinstance(parent, ast.Call):
            fname = parent.func.id if isinstance(parent.func, ast.Name) \
                else None
            if fname not in _COERCIONS:
                return "other"
        cur = parent


def _gate_sites(pctx: ProjectContext):
    cached = getattr(pctx, "_gate_sites", None)
    if cached is not None:
        return cached
    reads: Dict[str, List[Tuple[Site, str]]] = {}
    declared_on: Dict[str, Site] = {}
    declared: Set[str] = set()
    for mod in pctx.modules:
        aliases = _rtconfig_aliases(mod)
        f = mod.filename
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.ctx, ast.Load)
                    and terminal_name(node.value) in aliases
                    and node.attr in catalog.GATES):
                reads.setdefault(node.attr, []).append((
                    (f, node.lineno, node.col_offset),
                    _read_context(mod, node),
                ))
            elif isinstance(node, ast.Call):
                fn = node.func
                if not isinstance(fn, ast.Attribute):
                    continue
                if (fn.attr == "get" and terminal_name(fn.value) in aliases
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and node.args[0].value in catalog.GATES):
                    reads.setdefault(node.args[0].value, []).append((
                        (f, node.lineno, node.col_offset),
                        _read_context(mod, node),
                    ))
                elif (fn.attr == "declare" and len(node.args) >= 3
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    name = node.args[0].value
                    declared.add(name)
                    if (isinstance(node.args[1], ast.Name)
                            and node.args[1].id == "bool"
                            and isinstance(node.args[2], ast.Constant)
                            and node.args[2].value is True):
                        declared_on[name] = (f, node.lineno,
                                             node.col_offset)
    pctx._gate_sites = (reads, declared_on, declared)
    return reads, declared_on, declared


@register_project("RT402", FAMILY_PROTOCOL,
                  "behavior-gate parity vs rt_config declarations")
def check_gates(pctx: ProjectContext) -> List[Finding]:
    reads, declared_on, declared = _gate_sites(pctx)
    findings: List[Finding] = []
    for gate, entry in catalog.GATES.items():
        if entry.get("waive"):
            continue
        sites = reads.get(gate, [])
        if sites and not any(kind in ("branch", "assign", "return")
                             for _s, kind in sites):
            (f, line, col), _k = sites[0]
            findings.append(Finding(
                "RT402",
                f"gate '{gate}' is read here but never branched on "
                "(no if/while/ternary test, no assignment a later "
                "branch could test, no return) — the off-path is "
                "unreachable, so RT_"
                f"{gate.upper()}=0 silently does nothing",
                f, line, col,
            ))
        if not pctx.complete:
            continue
        if not sites:
            findings.append(_absence(
                "RT402",
                f"cataloged gate '{gate}' is never read anywhere in the "
                "tree — a default-ON behavior gate nobody consults is "
                "dead config surface; wire it up or retire it from "
                "rt_config and lint/catalog.py",
            ))
        if declared and gate not in declared_on:
            findings.append(_absence(
                "RT402",
                f"cataloged gate '{gate}' is not declared as a "
                "default-ON bool in rt_config — catalog/config drift; "
                "run --regen or fix the declaration",
            ))
    if pctx.complete and declared:
        for gate, (f, line, col) in sorted(declared_on.items()):
            if gate not in catalog.GATES:
                findings.append(Finding(
                    "RT402",
                    f"default-ON behavior gate '{gate}' declared here "
                    "is missing from lint/catalog.py GATES — run "
                    "``python -m ray_tpu.lint --regen`` so the gate "
                    "catalog cannot drift from the declarations",
                    f, line, col,
                ))
    return findings


# -------------------------------------------------------- faultpoint scan

def _fire_sites(pctx: ProjectContext) -> Dict[str, List[Site]]:
    cached = getattr(pctx, "_fire_sites", None)
    if cached is not None:
        return cached
    sites: Dict[str, List[Site]] = {}
    for mod in pctx.modules:
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("fire", "async_fire")
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                sites.setdefault(node.args[0].value, []).append(
                    (mod.filename, node.lineno, node.col_offset))
    pctx._fire_sites = sites
    return sites


@register_project("RT403", FAMILY_PROTOCOL,
                  "faultpoint drift vs the chaos catalog")
def check_faultpoints(pctx: ProjectContext) -> List[Finding]:
    sites = _fire_sites(pctx)
    findings: List[Finding] = []
    for name, locs in sorted(sites.items()):
        if name in catalog.FAULTPOINTS:
            continue
        if any(name.startswith(p) for p in catalog.DYNAMIC_FIRE_PREFIXES):
            continue
        f, line, col = locs[0]
        findings.append(Finding(
            "RT403",
            f"faultpoint '{name}' is fired here but absent from "
            "lint/catalog.py FAULTPOINTS — every injection point must "
            "be pinned (and chaos-matrixed or waived) so the fire-site "
            "set and the chaos matrix cannot drift apart; run "
            "``python -m ray_tpu.lint --regen``",
            f, line, col,
        ))
    if not pctx.complete:
        return findings
    for name, entry in catalog.FAULTPOINTS.items():
        if name not in sites:
            findings.append(_absence(
                "RT403",
                f"cataloged faultpoint '{name}' has no fire site "
                "anywhere in the tree — stale catalog entry; run "
                "``python -m ray_tpu.lint --regen``",
            ))
        elif not entry.get("matrixed") and not entry.get("waive"):
            f, line, col = sites[name][0]
            findings.append(Finding(
                "RT403",
                f"faultpoint '{name}' is live but appears in no "
                "chaos-matrix spec and carries no waiver — the matrix "
                "can no longer prove the failure path works; add a "
                "spec to CHAOS_SPECS (tests/test_faultpoints.py) or a "
                "waive reason in lint/catalog.py",
                f, line, col,
            ))
    return findings


# ------------------------------------------------------------- phase scan

def _phase_sites(pctx: ProjectContext):
    cached = getattr(pctx, "_phase_sites", None)
    if cached is not None:
        return cached
    stages: Dict[str, List[Site]] = {}
    phases: Dict[str, List[Site]] = {}
    taskpath_phases: Optional[Tuple[Tuple[str, ...], Site]] = None
    for mod in pctx.modules:
        f = mod.filename
        is_taskpath = os.path.basename(f) == "taskpath.py"
        for node in ast.walk(mod.tree):
            if (is_taskpath and isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == "PHASES"
                            for t in node.targets)
                    and isinstance(node.value, ast.Tuple)):
                taskpath_phases = (
                    tuple(e.value for e in node.value.elts
                          if isinstance(e, ast.Constant)),
                    (f, node.lineno, node.col_offset),
                )
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            lit = (node.args[0].value
                   if node.args and isinstance(node.args[0], ast.Constant)
                   and isinstance(node.args[0].value, str) else None)
            if name == "record_phase" and lit is not None:
                stages.setdefault(lit, []).append(
                    (f, node.lineno, node.col_offset))
            elif (name == "record" and lit is not None
                    and lit.startswith("task.")
                    and dotted(fn) == "flight.record"):
                stages.setdefault(lit[len("task."):], []).append(
                    (f, node.lineno, node.col_offset))
            if name in ("record_phase", "observe_phase"):
                for kw in node.keywords:
                    if (kw.arg == "phase"
                            and isinstance(kw.value, ast.Constant)
                            and isinstance(kw.value.value, str)):
                        phases.setdefault(kw.value.value, []).append(
                            (f, kw.value.lineno, kw.value.col_offset))
    pctx._phase_sites = (stages, phases, taskpath_phases)
    return stages, phases, taskpath_phases


@register_project("RT404", FAMILY_PROTOCOL,
                  "taskpath phase-catalog drift")
def check_phases(pctx: ProjectContext) -> List[Finding]:
    stages, phases, taskpath_phases = _phase_sites(pctx)
    findings: List[Finding] = []
    for stage, locs in sorted(stages.items()):
        if stage in catalog.STAGES:
            continue
        f, line, col = locs[0]
        findings.append(Finding(
            "RT404",
            f"taskpath span stage '{stage}' recorded here is absent "
            "from lint/catalog.py STAGES — the analyzer's "
            "named+residual==wall decomposition silently lumps unknown "
            "spans into the residual; run "
            "``python -m ray_tpu.lint --regen`` and teach "
            "taskpath.task_breakdown about the new stage",
            f, line, col,
        ))
    for phase, locs in sorted(phases.items()):
        if phase in catalog.PHASES:
            continue
        f, line, col = locs[0]
        findings.append(Finding(
            "RT404",
            f"phase label '{phase}' observed here is absent from the "
            "pinned PHASES table — rt_task_phase_seconds grows a "
            "series the breakdown tables will never show; add it to "
            "taskpath.PHASES and run --regen",
            f, line, col,
        ))
    if pctx.complete and taskpath_phases is not None:
        table, (f, line, col) = taskpath_phases
        if table != tuple(catalog.PHASES):
            findings.append(Finding(
                "RT404",
                "taskpath.PHASES and lint/catalog.py PHASES disagree "
                f"({list(table)} vs {list(catalog.PHASES)}) — run "
                "``python -m ray_tpu.lint --regen``",
                f, line, col,
            ))
    return findings
