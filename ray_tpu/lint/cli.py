"""CLI for the analyzer: ``python -m ray_tpu.lint <paths>`` (also wired
into the main CLI as ``raytpu lint``).

Exit code 0 = clean, 1 = findings, 2 = usage error. ``--json`` emits a
machine-readable finding list (each record carries a ``family`` field so
dashboards can filter) for ingestion. ``--regen`` rewrites
``lint/catalog.py`` from the tree (see ``catalog_gen.py``); on a clean
tree it is a no-op.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Sequence

from ray_tpu.lint.base import all_rules, lint_paths

_FAMILY_TITLES = (
    ("A", "user code (decoration-time gate, RAY_TPU_LINT=1)"),
    ("B", "framework thread+lock discipline (_private/, serve/, "
          "--framework)"),
    ("C", "asyncio/thread concurrency hazards (same scope as B)"),
    ("D", "protocol invariants vs lint/catalog.py (project-scope: "
          "directory scans and --select RT4)"),
)


def run(paths: Sequence[str], json_out: bool = False,
        framework: Optional[bool] = None,
        select: Optional[Sequence[str]] = None,
        stream=None) -> int:
    stream = stream if stream is not None else sys.stdout
    findings = lint_paths(paths, framework=framework, select=select)
    if json_out:
        json.dump([f.to_dict() for f in findings], stream, indent=2)
        stream.write("\n")
    else:
        for f in findings:
            print(f.format(), file=stream)
        n = len(findings)
        print(f"{n} finding{'s' if n != 1 else ''}", file=stream)
    return 1 if findings else 0


def list_rules(stream=None) -> int:
    stream = stream if stream is not None else sys.stdout
    rules = all_rules()
    for family, title in _FAMILY_TITLES:
        fam_rules = sorted(
            (r for r in rules.values() if r.family == family),
            key=lambda r: r.rule_id,
        )
        if not fam_rules:
            continue
        print(f"Family {family} — {title}", file=stream)
        for rule in fam_rules:
            print(f"  {rule.rule_id}  {rule.summary}", file=stream)
        print("", file=stream)
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m ray_tpu.lint",
        description="AST-based distributed-correctness analyzer "
                    "(RT1xx: user code, RT2xx: framework locks, "
                    "RT3xx: concurrency, RT4xx: protocol invariants)",
    )
    p.add_argument("paths", nargs="*", help="files or directories to lint")
    p.add_argument("--json", action="store_true", dest="json_out",
                   help="emit findings as JSON (records carry a 'family' "
                        "field)")
    p.add_argument("--framework", action="store_true",
                   help="run Families B+C (framework) rules on every "
                        "file, not just ray_tpu/_private/ and serve/")
    p.add_argument("--select", default=None,
                   help="comma-separated rule-id prefixes to run "
                        "(e.g. RT2 or RT101,RT203 or RT2,RT3,RT4)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule registry grouped by family and "
                        "exit")
    p.add_argument("--regen", action="store_true",
                   help="regenerate lint/catalog.py from the tree "
                        "(derived sections rebuild, waivers carry over; "
                        "no-op on a clean tree)")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        return list_rules()
    if args.regen:
        from ray_tpu.lint import catalog_gen

        changed = catalog_gen.regen()
        path = catalog_gen.catalog_path()
        print(f"{path}: {'regenerated' if changed else 'up to date'}")
        return 0
    if not args.paths:
        build_parser().error("the following arguments are required: paths")
    select = ([s.strip() for s in args.select.split(",") if s.strip()]
              if args.select else None)
    return run(args.paths, json_out=args.json_out,
               framework=True if args.framework else None, select=select)
