"""CLI for the analyzer: ``python -m ray_tpu.lint <paths>`` (also wired
into the main CLI as ``raytpu lint``).

Exit code 0 = clean, 1 = findings, 2 = usage error. ``--json`` emits a
machine-readable finding list for dashboard ingestion.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Sequence

from ray_tpu.lint.base import RULES, lint_paths


def run(paths: Sequence[str], json_out: bool = False,
        framework: Optional[bool] = None,
        select: Optional[Sequence[str]] = None,
        stream=None) -> int:
    stream = stream if stream is not None else sys.stdout
    findings = lint_paths(paths, framework=framework, select=select)
    if json_out:
        json.dump([f.to_dict() for f in findings], stream, indent=2)
        stream.write("\n")
    else:
        for f in findings:
            print(f.format(), file=stream)
        n = len(findings)
        print(f"{n} finding{'s' if n != 1 else ''}", file=stream)
    return 1 if findings else 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m ray_tpu.lint",
        description="AST-based distributed-correctness analyzer "
                    "(rules RT1xx: user code, RT2xx: framework)",
    )
    p.add_argument("paths", nargs="*", help="files or directories to lint")
    p.add_argument("--json", action="store_true", dest="json_out",
                   help="emit findings as JSON")
    p.add_argument("--framework", action="store_true",
                   help="run Family B (framework) rules on every file, "
                        "not just ray_tpu/_private/")
    p.add_argument("--select", default=None,
                   help="comma-separated rule-id prefixes to run "
                        "(e.g. RT2 or RT101,RT203)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule registry and exit")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        # Ensure the registry is populated.
        from ray_tpu.lint import framework_rules, user_rules  # noqa: F401

        for rule in sorted(RULES.values(), key=lambda r: r.rule_id):
            print(f"{rule.rule_id}  [family {rule.family}]  {rule.summary}")
        return 0
    if not args.paths:
        build_parser().error("the following arguments are required: paths")
    select = ([s.strip() for s in args.select.split(",") if s.strip()]
              if args.select else None)
    return run(args.paths, json_out=args.json_out,
               framework=True if args.framework else None, select=select)
