"""Family A — rules for user code passed to ``@ray_tpu.remote``.

RT101  closure capture of a non-picklable / ownership-breaking value
RT102  blocking ``ray_tpu.get()``/``wait()`` inside a task or actor method
RT103  dropped ``.remote()`` result (lost exceptions, unawaited failures)
RT104  resource request the scheduler can never place

These mirror checks the reference engine performs at runtime (task spec
validation, serialization failure at submission, bounded-worker deadlock
detection) — here they fire before a bad task ever ships.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from ray_tpu.lint.base import (
    FAMILY_USER,
    Finding,
    ModuleContext,
    dotted,
    register,
)

# Constructors whose results cannot cross a pickle boundary (or, for
# ObjectRef producers, must not cross it via closure capture). Bare names
# cover ``from threading import Lock``-style imports.
_NONPICKLABLE_CTORS = {
    "threading.Lock": "a threading.Lock",
    "threading.RLock": "a threading.RLock",
    "threading.Semaphore": "a threading.Semaphore",
    "threading.BoundedSemaphore": "a threading.Semaphore",
    "threading.Condition": "a threading.Condition",
    "threading.Event": "a threading.Event",
    "multiprocessing.Lock": "a multiprocessing.Lock",
    "socket.socket": "a socket",
    "socket.create_connection": "a socket",
    "open": "an open file handle",
}


def _remote_targets(ctx: ModuleContext) -> Iterator[Tuple[ast.AST, str]]:
    """Yield (node, kind) for every remote-decorated def/class.

    kind: "task" for functions, "actor" for classes. In decoration-time
    mode (``ctx.assume_remote``) the first top-level def/class is the
    target even without a visible decorator.
    """
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if ctx.is_remote_decorated(node):
                yield node, "task"
        elif isinstance(node, ast.ClassDef):
            if ctx.is_remote_decorated(node):
                yield node, "actor"
    if ctx.assume_remote:
        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if not ctx.is_remote_decorated(node):
                    yield node, "task"
                break
            if isinstance(node, ast.ClassDef):
                if not ctx.is_remote_decorated(node):
                    yield node, "actor"
                break


def _local_names(fn: ast.AST) -> set:
    """Parameters plus every name the function binds itself."""
    names = set()
    args = fn.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs):
        names.add(a.arg)
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            names.add(node.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                names.add(alias.asname or alias.name.split(".")[0])
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)) and node is not fn:
            names.add(node.name)
    return names


def _enclosing_assignments(ctx: ModuleContext,
                           target: ast.AST) -> Dict[str, ast.expr]:
    """name -> value expression for simple assignments in every scope that
    lexically encloses ``target`` (module body and outer functions)."""
    out: Dict[str, ast.expr] = {}

    def collect(body):
        for stmt in body:
            if isinstance(stmt, ast.Assign):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        out[tgt.id] = stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                if isinstance(stmt.target, ast.Name):
                    out[stmt.target.id] = stmt.value
            elif isinstance(stmt, ast.With):
                # `with open(...) as f:` binds f to an open handle
                for item in stmt.items:
                    if (item.optional_vars is not None
                            and isinstance(item.optional_vars, ast.Name)):
                        out[item.optional_vars.id] = item.context_expr
                collect(stmt.body)

    # Walk down the enclosure chain module -> ... -> target, collecting
    # assignments at each level above the target itself.
    def descend(body) -> bool:
        collect(body)
        for stmt in body:
            if stmt is target:
                return True
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(n is target for n in ast.walk(stmt)):
                    return descend(stmt.body)
        return False

    descend(ctx.tree.body)
    return out


def _capture_kind(ctx: ModuleContext, value: ast.expr) -> Optional[str]:
    """If ``value`` produces a non-picklable / ownership-breaking object,
    describe it."""
    if not isinstance(value, ast.Call):
        return None
    name = dotted(value.func)
    if name in _NONPICKLABLE_CTORS:
        return _NONPICKLABLE_CTORS[name]
    if name is not None and name.split(".")[-1] in ("Lock", "RLock"):
        return "a lock"
    if isinstance(value.func, ast.Attribute) and value.func.attr == "remote":
        return "a live ObjectRef (from .remote())"
    if ctx.is_ray_api_call(value, ("put",)):
        return "a live ObjectRef (from ray_tpu.put())"
    return None


@register("RT101", FAMILY_USER,
          "remote function captures a non-picklable value from an "
          "enclosing scope")
def check_closure_capture(ctx: ModuleContext) -> List[Finding]:
    findings = []
    for fn, kind in _remote_targets(ctx):
        if kind != "task":
            continue
        assigns = _enclosing_assignments(ctx, fn)
        if not assigns:
            continue
        locals_ = _local_names(fn)
        seen = set()
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)):
                continue
            name = node.id
            if name in locals_ or name in seen or name not in assigns:
                continue
            desc = _capture_kind(ctx, assigns[name])
            if desc is None:
                continue
            seen.add(name)
            hint = ("pass the ref as an argument so ownership/borrow "
                    "bookkeeping can track it"
                    if "ObjectRef" in desc else
                    "create it inside the task or pass picklable state "
                    "instead")
            findings.append(Finding(
                "RT101",
                f"remote function '{fn.name}' captures {desc} "
                f"('{name}') from an enclosing scope; it cannot be "
                f"pickled into the task spec — {hint}",
                ctx.filename, node.lineno, node.col_offset,
            ))
    return findings


def _sync_bodies(node: ast.AST, kind: str):
    """Yield (owner_name, body_root) for code that runs inside the task:
    the function itself, or each method of an actor class."""
    if kind == "task":
        yield node.name, node
    else:
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield f"{node.name}.{stmt.name}", stmt


@register("RT102", FAMILY_USER,
          "blocking get()/wait() inside a remote task or actor method")
def check_blocking_get(ctx: ModuleContext) -> List[Finding]:
    findings = []
    for node, kind in _remote_targets(ctx):
        for owner, body in _sync_bodies(node, kind):
            for sub in ast.walk(body):
                if not isinstance(sub, ast.Call):
                    continue
                if ctx.is_ray_api_call(sub, ("get", "wait")):
                    api = (sub.func.attr if isinstance(sub.func, ast.Attribute)
                           else ctx.from_ray.get(sub.func.id, "get"))
                    findings.append(Finding(
                        "RT102",
                        f"'{owner}' calls ray_tpu.{api}() inside a remote "
                        f"{'task' if kind == 'task' else 'actor method'}: "
                        "with a bounded worker pool this deadlocks when "
                        "every worker blocks waiting on tasks that cannot "
                        "be scheduled — restructure so the driver awaits, "
                        "or pass resolved values as arguments",
                        ctx.filename, sub.lineno, sub.col_offset,
                    ))
    return findings


@register("RT103", FAMILY_USER,
          "dropped .remote() result — exceptions in the task are lost")
def check_dropped_remote(ctx: ModuleContext) -> List[Finding]:
    findings = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Expr):
            continue
        value = node.value
        if not (isinstance(value, ast.Call)
                and isinstance(value.func, ast.Attribute)
                and value.func.attr == "remote"):
            continue
        findings.append(Finding(
            "RT103",
            "result of .remote() is discarded: the returned ObjectRef is "
            "the only carrier of the task's exceptions — keep it and "
            "get()/wait() it (or assign to _ and suppress deliberately)",
            ctx.filename, node.lineno, node.col_offset,
        ))
    return findings


_RESOURCE_KWARGS = ("num_cpus", "num_gpus", "num_tpus", "num_returns")


def _const_number(node: ast.expr):
    """Numeric value of a literal, unwrapping unary +/- (``-1`` parses as
    UnaryOp, not Constant). None if not a numeric literal."""
    sign = 1
    while isinstance(node, ast.UnaryOp) and isinstance(
            node.op, (ast.UAdd, ast.USub)):
        if isinstance(node.op, ast.USub):
            sign = -sign
        node = node.operand
    if (isinstance(node, ast.Constant)
            and isinstance(node.value, (int, float))
            and not isinstance(node.value, bool)):
        return sign * node.value
    return None


def _resource_findings(ctx: ModuleContext, call: ast.Call,
                       where: str) -> List[Finding]:
    findings = []
    for kw in call.keywords:
        if kw.arg in _RESOURCE_KWARGS:
            v = _const_number(kw.value)
            if v is None:
                continue
            if v < 0:
                findings.append(Finding(
                    "RT104",
                    f"{where}: {kw.arg}={v!r} is negative — no node can "
                    "ever satisfy it, the task would pend forever",
                    ctx.filename, kw.value.lineno, kw.value.col_offset,
                ))
            elif kw.arg == "num_tpus" and float(v) != int(v):
                findings.append(Finding(
                    "RT104",
                    f"{where}: num_tpus={v!r} is fractional — TPU cores "
                    "are whole devices; the scheduler can never place a "
                    "fractional core request",
                    ctx.filename, kw.value.lineno, kw.value.col_offset,
                ))
        elif kw.arg == "resources" and isinstance(kw.value, ast.Dict):
            for k, v in zip(kw.value.keys, kw.value.values):
                num = _const_number(v)
                if num is None:
                    continue
                key = k.value if isinstance(k, ast.Constant) else None
                if num < 0:
                    findings.append(Finding(
                        "RT104",
                        f"{where}: resources[{key!r}]={num!r} is "
                        "negative — unplaceable",
                        ctx.filename, v.lineno, v.col_offset,
                    ))
                elif key in ("CPU", "GPU", "TPU"):
                    findings.append(Finding(
                        "RT104",
                        f"{where}: pass {key} via num_{key.lower()}s=, not "
                        "the resources dict — the explicit option wins and "
                        "this entry is silently ambiguous",
                        ctx.filename, v.lineno, v.col_offset,
                    ))
    return findings


@register("RT104", FAMILY_USER,
          "resource request the scheduler can never place")
def check_resources(ctx: ModuleContext) -> List[Finding]:
    findings = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                tgt = dec.func
                is_remote_dec = (
                    (isinstance(tgt, ast.Attribute) and tgt.attr == "remote"
                     and isinstance(tgt.value, ast.Name)
                     and tgt.value.id in ctx.ray_aliases)
                    or (isinstance(tgt, ast.Name)
                        and ctx.from_ray.get(tgt.id) == "remote")
                )
                if is_remote_dec:
                    findings.extend(_resource_findings(
                        ctx, dec, f"@remote on '{node.name}'"
                    ))
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr == "options"):
            findings.extend(_resource_findings(ctx, node, ".options()"))
    return findings


def validate_options(options: dict, where: str) -> List[str]:
    """Value-based RT104 for decoration time: validate an options dict
    directly (no AST needed — .options() merges are dynamic)."""
    problems = []
    for key in _RESOURCE_KWARGS:
        v = options.get(key)
        if v is None or isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        if v < 0:
            problems.append(f"{where}: {key}={v!r} is negative — unplaceable")
        elif key == "num_tpus" and float(v) != int(v):
            problems.append(
                f"{where}: num_tpus={v!r} is fractional — TPU cores are "
                "whole devices"
            )
    res = options.get("resources")
    if isinstance(res, dict):
        for k, v in res.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool) and v < 0:
                problems.append(
                    f"{where}: resources[{k!r}]={v!r} is negative — "
                    "unplaceable"
                )
            elif k in ("CPU", "GPU", "TPU"):
                problems.append(
                    f"{where}: pass {k} via num_{k.lower()}s=, not the "
                    "resources dict"
                )
    return problems
