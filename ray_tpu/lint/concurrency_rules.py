"""Family C — asyncio/thread concurrency hazards in framework code.

RT301  blocking call inside an ``async def`` (stalls the core loop)
RT302  event-loop object touched from a thread without *_threadsafe
RT303  fire-and-forget ``create_task`` with no exception sink
RT304  ``await`` while holding a sync ``threading.Lock``
RT305  shared attribute written from both a thread and a coroutine
       with no lock on either path (best-effort, tuned for low noise)

The driver's core event loop shares submission, reply settling and
bookkeeping (see ROADMAP "driver loop" item); these rules encode the
defect classes that machine actually produces: a ``time.sleep`` or
no-timeout ``Future.result()`` in a coroutine stalls every in-flight
task at once (RT301); ``loop.create_task`` from the ring pump thread
corrupts the loop's ready queue (RT302, asyncio's documented
thread-unsafety); a dropped ``create_task`` handle swallows its
exception forever (RT303, use ``_private.asyncio_util.spawn_logged``);
an ``await`` under a sync lock deadlocks against executor threads that
want the same lock (RT304).

Deliberate executor-thread coroutine helpers can be allowlisted with an
``@executor_thread``-style decorator (any decorator whose name contains
``executor_thread``) or a ``# raytpu: executor-thread`` comment on the
``def`` line; per-line ``# raytpu: ignore[RULE]`` works as everywhere.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.lint.base import (
    FAMILY_CONCURRENCY,
    Finding,
    ModuleContext,
    dotted,
    register,
    terminal_name,
)

# ------------------------------------------------------------------ RT301

# Dotted call targets that block the calling thread outright.
_BLOCKING_DOTTED = {
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "select.select", "os.waitpid",
    "socket.create_connection",
}
# Attribute calls that block regardless of receiver (socket I/O,
# subprocess handshakes). Generic names (.send/.join/.read) stay out.
_BLOCKING_ATTRS = {
    "recv", "recvfrom", "recv_into", "accept", "sendall", "communicate",
}

_EXECUTOR_MARK = "raytpu: executor-thread"


def _is_executor_allowlisted(ctx: ModuleContext, fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = terminal_name(target) or ""
        if "executor_thread" in name:
            return True
    line = getattr(fn, "lineno", 0)
    if 1 <= line <= len(ctx.lines):
        if _EXECUTOR_MARK in ctx.lines[line - 1]:
            return True
    return False


def _queueish(node: ast.AST) -> bool:
    name = (terminal_name(node) or "").lower()
    return name in ("q",) or "queue" in name


class _AsyncBlockWalker(ast.NodeVisitor):
    """RT301: blocking sync calls lexically inside async defs.

    Awaited calls are fine by construction (``await q.get()`` parks the
    coroutine, not the loop); ``fut.result()`` guarded by a
    ``fut.done()`` test in an enclosing ``if`` is a completed-future
    fast path, not a block.
    """

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.findings: List[Finding] = []
        self._async_depth = 0
        self._tests: List[ast.expr] = []  # enclosing if/while conditions
        self._awaited: Set[int] = set()   # id() of calls under an Await

    def visit_FunctionDef(self, node):
        # A sync def nested in a coroutine runs wherever it is called
        # (often an executor thread) — out of scope here.
        depth, self._async_depth = self._async_depth, 0
        self.generic_visit(node)
        self._async_depth = depth

    def visit_AsyncFunctionDef(self, node):
        if _is_executor_allowlisted(self.ctx, node):
            return
        self._async_depth += 1
        self.generic_visit(node)
        self._async_depth -= 1

    def visit_Await(self, node):
        if isinstance(node.value, ast.Call):
            self._awaited.add(id(node.value))
        self.generic_visit(node)

    def _visit_test_body(self, node):
        self._tests.append(node.test)
        self.generic_visit(node)
        self._tests.pop()

    visit_If = _visit_test_body
    visit_While = _visit_test_body

    def _done_guarded(self, recv: Optional[str]) -> bool:
        if not recv:
            return False
        for test in self._tests:
            for sub in ast.walk(test):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "done"
                        and dotted(sub.func.value) == recv):
                    return True
        return False

    def _blocking_desc(self, call: ast.Call) -> Optional[str]:
        if self.ctx.is_time_sleep(call):
            return "time.sleep()"
        name = dotted(call.func)
        if name in _BLOCKING_DOTTED:
            return f"{name}()"
        if not isinstance(call.func, ast.Attribute):
            return None
        attr = call.func.attr
        if attr in _BLOCKING_ATTRS:
            return f".{attr}()"
        has_timeout = any(k.arg == "timeout" for k in call.keywords)
        if attr == "result" and not call.args and not has_timeout:
            if self._done_guarded(dotted(call.func.value)):
                return None
            return ".result() with no timeout"
        if (attr == "get" and not call.args and not has_timeout
                and _queueish(call.func.value)):
            return ".get() with no timeout"
        return None

    def visit_Call(self, node):
        if self._async_depth and id(node) not in self._awaited:
            desc = self._blocking_desc(node)
            if desc is not None:
                self.findings.append(Finding(
                    "RT301",
                    f"blocking {desc} inside an async def — the call "
                    "stalls the whole event loop (every in-flight "
                    "task/reply on it), not just this coroutine; await "
                    "the async form, add a timeout, or move the work to "
                    "run_in_executor (mark deliberate executor-thread "
                    f"helpers with '# {_EXECUTOR_MARK}')",
                    self.ctx.filename, node.lineno, node.col_offset,
                ))
        self.generic_visit(node)


@register("RT301", FAMILY_CONCURRENCY,
          "blocking call inside an async def stalls the event loop")
def check_async_blocking(ctx: ModuleContext) -> List[Finding]:
    walker = _AsyncBlockWalker(ctx)
    walker.visit(ctx.tree)
    return walker.findings


# ------------------------------------------------------- thread reachability

def _local_functions(tree) -> Dict[Tuple[Optional[str], str], ast.AST]:
    """(class or None, name) -> def node, for module-level and one-level
    class-nested functions (the shapes this codebase uses)."""
    out: Dict[Tuple[Optional[str], str], ast.AST] = {}

    def add(node, cls):
        out[(cls, node.name)] = node

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            add(node, None)
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    add(sub, node.name)
    return out


def _callable_ref_name(node: ast.AST) -> Optional[str]:
    """``self._pump`` / ``_spawn`` / ``conn.close`` -> terminal name."""
    return terminal_name(node)


_THREADSAFE_BRIDGES = {"call_soon_threadsafe", "run_coroutine_threadsafe"}


class _ThreadEntryCollector(ast.NodeVisitor):
    """Find function names that run on non-loop threads: passed as
    ``threading.Thread(target=...)``, executor ``.submit(fn)``,
    ``loop.run_in_executor(None, fn)``, or a plane-queue ``worker=``
    callback (round 20: ``PlaneQueue(..., worker=fn)`` runs ``fn`` on
    the plane's dedicated thread) — plus locally-defined callables
    those functions call (one same-module transitive closure)."""

    def __init__(self, tree):
        self.tree = tree
        self.entry_names: Set[str] = set()

    def visit_Call(self, node):
        fn = node.func
        attr = fn.attr if isinstance(fn, ast.Attribute) else None
        name = dotted(fn) or ""
        if name.endswith("Thread") or attr == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    ref = _callable_ref_name(kw.value)
                    if ref:
                        self.entry_names.add(ref)
        elif attr == "submit" and node.args:
            ref = _callable_ref_name(node.args[0])
            if ref:
                self.entry_names.add(ref)
        elif attr == "run_in_executor" and len(node.args) >= 2:
            ref = _callable_ref_name(node.args[1])
            if ref:
                self.entry_names.add(ref)
        for kw in node.keywords:
            # Plane handoff idiom (round 20): a ``worker=`` callback —
            # ``PlaneQueue(..., worker=fn)`` — drains batches on the
            # plane's own thread, never the loop.
            if kw.arg == "worker":
                ref = _callable_ref_name(kw.value)
                if ref:
                    self.entry_names.add(ref)
        self.generic_visit(node)


def _loop_side(name: str) -> bool:
    """Naming convention: ``*_from_loop`` / ``*_on_loop`` helpers are
    declared loop-thread-only (their callers carry the runtime
    ``get_running_loop() is loop`` dispatch the AST cannot see)."""
    return name.endswith("_from_loop") or name.endswith("_on_loop")


def _thread_reachable(ctx: ModuleContext) -> Set[Tuple[Optional[str], str]]:
    """Keys of ``_local_functions`` reachable from a thread entry point
    without crossing a *_threadsafe bridge (cached per module).

    ``async def``s are excluded on both ends: a coroutine function
    passed to a thread would never run its body there, and the bodies
    execute on whichever loop awaits them.
    """
    cached = getattr(ctx, "_thread_reachable", None)
    if cached is not None:
        return cached
    funcs = _local_functions(ctx.tree)
    collector = _ThreadEntryCollector(ctx.tree)
    collector.visit(ctx.tree)

    def eligible(key) -> bool:
        return (not isinstance(funcs[key], ast.AsyncFunctionDef)
                and not _loop_side(key[1]))

    # Seed: every def whose name was used as a thread/executor target.
    work = [k for k in funcs
            if k[1] in collector.entry_names and eligible(k)]
    seen: Set[Tuple[Optional[str], str]] = set(work)
    while work:
        cls, name = work.pop()
        node = funcs[(cls, name)]
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            # Crossing call_soon_threadsafe(...) re-enters the loop
            # thread; callables referenced in its args are safe.
            if (isinstance(fn, ast.Attribute)
                    and fn.attr in _THREADSAFE_BRIDGES):
                continue
            callee = _callable_ref_name(fn)
            if not callee:
                continue
            for key in ((cls, callee), (None, callee)):
                if key in funcs and key not in seen and eligible(key):
                    seen.add(key)
                    work.append(key)
    ctx._thread_reachable = seen
    return seen


def _in_threadsafe_lambda(stack: List[ast.AST]) -> bool:
    """Is the innermost frame a lambda/def passed to a *_threadsafe
    bridge (so it executes on the loop thread after all)?"""
    for node in stack:
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _THREADSAFE_BRIDGES):
            return True
    return False


_LOOP_TOUCH_ATTRS = {"create_task", "call_soon", "call_later", "call_at",
                     "stop"}


class _LoopTouchWalker(ast.NodeVisitor):
    """RT302: direct loop manipulation in thread-reachable functions."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.findings: List[Finding] = []
        self._reach = _thread_reachable(ctx)
        self._class: Optional[str] = None
        # Stack of "the code here runs on a thread" booleans, one per
        # enclosing def/lambda. Nested defs and lambdas are deferred
        # callbacks whose execution context the AST cannot prove, so
        # they reset to False (best-effort, no false positives).
        self._frames: List[bool] = []
        self._stack: List[ast.AST] = []

    @property
    def _active(self) -> bool:
        return bool(self._frames and self._frames[-1])

    def visit_ClassDef(self, node):
        prev, self._class = self._class, node.name
        self.generic_visit(node)
        self._class = prev

    def _visit_fn(self, node):
        key = (self._class, node.name)
        alt = (None, node.name)
        nested = bool(self._frames)
        active = (not nested and (key in self._reach
                                  or alt in self._reach))
        self._frames.append(active)
        self.generic_visit(node)
        self._frames.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Lambda(self, node):
        self._frames.append(False)
        self.generic_visit(node)
        self._frames.pop()

    def visit_Call(self, node):
        self._stack.append(node)
        try:
            if self._active and not _in_threadsafe_lambda(self._stack):
                fn = node.func
                attr = fn.attr if isinstance(fn, ast.Attribute) else None
                recv = (terminal_name(fn.value) or "" if
                        isinstance(fn, ast.Attribute) else "")
                loopish = "loop" in recv.lower()
                ensure = dotted(fn) in ("asyncio.ensure_future",)
                if ensure or (attr == "create_task") or (
                        loopish and attr in _LOOP_TOUCH_ATTRS):
                    what = dotted(fn) or f".{attr}"
                    self.findings.append(Finding(
                        "RT302",
                        f"{what}() from a function reachable from a "
                        "thread entry point (Thread target / executor "
                        "submit) — asyncio loops are not thread-safe; "
                        "hop through loop.call_soon_threadsafe(...) or "
                        "asyncio.run_coroutine_threadsafe(...) instead",
                        self.ctx.filename, node.lineno, node.col_offset,
                    ))
            self.generic_visit(node)
        finally:
            self._stack.pop()


@register("RT302", FAMILY_CONCURRENCY,
          "event-loop object touched from a non-loop thread")
def check_loop_from_thread(ctx: ModuleContext) -> List[Finding]:
    walker = _LoopTouchWalker(ctx)
    walker.visit(ctx.tree)
    return walker.findings


# ------------------------------------------------------------------ RT303

def _is_spawn_call(node: ast.Call) -> bool:
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr in ("create_task",
                                                     "ensure_future"):
        return True
    return False


@register("RT303", FAMILY_CONCURRENCY,
          "fire-and-forget create_task with no exception sink")
def check_dropped_task(ctx: ModuleContext) -> List[Finding]:
    findings = []
    for node in ast.walk(ctx.tree):
        call = None
        if (isinstance(node, ast.Expr) and isinstance(node.value, ast.Call)
                and _is_spawn_call(node.value)):
            call = node.value
        elif (isinstance(node, ast.Lambda)
                and isinstance(node.body, ast.Call)
                and _is_spawn_call(node.body)):
            # ``lambda: loop.create_task(...)`` handed to call_soon* —
            # the callback's return value is dropped just the same.
            call = node.body
        if call is None:
            continue
        findings.append(Finding(
            "RT303",
            "task handle dropped — if the coroutine raises, the "
            "exception is swallowed until interpreter shutdown (or "
            "forever); use _private.asyncio_util.spawn_logged(...) "
            "which attaches an exception-logging done callback, or "
            "store/await/gather the handle",
            ctx.filename, call.lineno, call.col_offset,
        ))
    return findings


# ------------------------------------------------------------------ RT304

def _is_lock_expr(node: ast.AST) -> bool:
    name = terminal_name(node)
    return name is not None and "lock" in name.lower()


class _AwaitUnderLockWalker(ast.NodeVisitor):
    """RT304: ``await`` inside a *sync* ``with <lock>``. The coroutine
    parks mid-critical-section holding a threading.Lock; any executor
    thread contending on it blocks until the loop resumes this
    coroutine — which may itself need that executor. ``async with``
    (asyncio locks) parks only coroutines and is fine."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.findings: List[Finding] = []
        self._held: List[str] = []

    def _visit_fn(self, node):
        held, self._held = self._held, []
        self.generic_visit(node)
        self._held = held

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_With(self, node):
        locks = [terminal_name(item.context_expr) or "lock"
                 for item in node.items
                 if _is_lock_expr(item.context_expr)]
        self._held.extend(locks)
        self.generic_visit(node)
        if locks:
            del self._held[len(self._held) - len(locks):]

    def visit_AsyncWith(self, node):
        # asyncio locks: not a thread hazard; do not track, do descend.
        self.generic_visit(node)

    def visit_Await(self, node):
        if self._held:
            self.findings.append(Finding(
                "RT304",
                f"await while holding sync lock '{self._held[-1]}' — "
                "the coroutine parks with the threading.Lock held and "
                "every executor thread contending on it stalls "
                "(deadlock if resuming needs that executor); release "
                "before awaiting or switch to asyncio.Lock",
                self.ctx.filename, node.lineno, node.col_offset,
            ))
        self.generic_visit(node)


@register("RT304", FAMILY_CONCURRENCY,
          "await while holding a sync threading.Lock")
def check_await_under_lock(ctx: ModuleContext) -> List[Finding]:
    walker = _AwaitUnderLockWalker(ctx)
    walker.visit(ctx.tree)
    return walker.findings


# ------------------------------------------------------------------ RT305

class _AttrWriteCollector(ast.NodeVisitor):
    """Per class: ``self.X = ...`` / ``self.X += ...`` sites, tagged
    with the enclosing function and whether a lock was lexically held."""

    def __init__(self):
        # class -> attr -> list of (fn_name, is_async_fn, under_lock,
        #                           line, col)
        self.writes: Dict[str, Dict[str, List[tuple]]] = {}
        self._class: Optional[str] = None
        self._fn: Optional[tuple] = None  # (name, is_async)
        self._lock_depth = 0

    def visit_ClassDef(self, node):
        prev, self._class = self._class, node.name
        self.generic_visit(node)
        self._class = prev

    def _visit_fn(self, node, is_async):
        prev, self._fn = self._fn, (node.name, is_async)
        self.generic_visit(node)
        self._fn = prev

    def visit_FunctionDef(self, node):
        self._visit_fn(node, False)

    def visit_AsyncFunctionDef(self, node):
        self._visit_fn(node, True)

    def _visit_with(self, node):
        locks = sum(1 for item in node.items
                    if _is_lock_expr(item.context_expr))
        self._lock_depth += locks
        self.generic_visit(node)
        self._lock_depth -= locks

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    def _record(self, target):
        if (self._class and self._fn
                and isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"):
            name, is_async = self._fn
            if name == "__init__" or "lock" in target.attr.lower():
                return
            self.writes.setdefault(self._class, {}).setdefault(
                target.attr, []
            ).append((name, is_async, self._lock_depth > 0,
                      target.lineno, target.col_offset))

    def visit_Assign(self, node):
        for t in node.targets:
            self._record(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._record(node.target)
        self.generic_visit(node)


@register("RT305", FAMILY_CONCURRENCY,
          "shared attribute written from both a thread and a coroutine "
          "without a lock")
def check_unlocked_shared_write(ctx: ModuleContext) -> List[Finding]:
    reach = _thread_reachable(ctx)
    thread_fns = {name for cls, name in reach}
    collector = _AttrWriteCollector()
    collector.visit(ctx.tree)
    findings = []
    for cls, attrs in collector.writes.items():
        for attr, sites in attrs.items():
            thread_sites = [s for s in sites
                            if s[0] in thread_fns and not s[1]]
            coro_sites = [s for s in sites if s[1]]
            if not thread_sites or not coro_sites:
                continue
            if any(s[2] for s in thread_sites + coro_sites):
                continue  # at least one side synchronizes; best-effort
            fn_t, _, _, line, col = thread_sites[0]
            fn_c = coro_sites[0][0]
            findings.append(Finding(
                "RT305",
                f"self.{attr} written from thread-reachable "
                f"'{fn_t}' and coroutine '{fn_c}' with no lock on "
                "either path — torn/lost updates under the GIL's "
                "bytecode-boundary interleaving; guard both writes "
                "with one lock or confine the attribute to one side",
                ctx.filename, line, col,
            ))
    return findings
