"""``ray_tpu.lint`` — AST-based distributed-correctness analyzer.

Entry points:

* CLI: ``python -m ray_tpu.lint <paths>`` / ``raytpu lint <paths>``
  (``--json`` for machine-readable output, ``--select RT2`` to scope).
* Decoration time: ``RAY_TPU_LINT=1`` makes ``@ray_tpu.remote`` raise
  :class:`~ray_tpu.exceptions.LintError` on Family-A findings.
* Self-check: ``tests/test_lint_self.py`` keeps ``ray_tpu/`` free of
  Family-B/C/D findings (``--framework`` over the whole tree).
* Catalog: ``python -m ray_tpu.lint --regen`` rebuilds
  ``lint/catalog.py``, the pinned wire/gate/chaos/phase tables Family D
  checks the code against.

See ``base.py`` for the rule model and ``PARITY.md`` ("Round-7",
"Round-17") for the rule catalog and suppression syntax
(``# raytpu: ignore[RULE]``).
"""
from ray_tpu.lint import (  # noqa: F401 (registry)
    concurrency_rules,
    framework_rules,
    invariant_rules,
    user_rules,
)
from ray_tpu.lint.base import (
    FAMILY_CONCURRENCY,
    FAMILY_FRAMEWORK,
    FAMILY_PROTOCOL,
    FAMILY_USER,
    PROJECT_RULES,
    RULES,
    Finding,
    ModuleContext,
    ProjectContext,
    Rule,
    all_rules,
    lint_file,
    lint_paths,
    lint_project,
    lint_source,
)
from ray_tpu.lint.decoration import (
    check_actor_class,
    check_remote_function,
    lint_enabled,
)

__all__ = [
    "FAMILY_CONCURRENCY",
    "FAMILY_FRAMEWORK",
    "FAMILY_PROTOCOL",
    "FAMILY_USER",
    "PROJECT_RULES",
    "RULES",
    "Finding",
    "ModuleContext",
    "ProjectContext",
    "Rule",
    "all_rules",
    "check_actor_class",
    "check_remote_function",
    "lint_enabled",
    "lint_file",
    "lint_paths",
    "lint_project",
    "lint_source",
]
