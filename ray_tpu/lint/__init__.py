"""``ray_tpu.lint`` — AST-based distributed-correctness analyzer.

Entry points:

* CLI: ``python -m ray_tpu.lint <paths>`` / ``raytpu lint <paths>``
  (``--json`` for machine-readable output, ``--select RT2`` to scope).
* Decoration time: ``RAY_TPU_LINT=1`` makes ``@ray_tpu.remote`` raise
  :class:`~ray_tpu.exceptions.LintError` on Family-A findings.
* Self-check: ``tests/test_lint_self.py`` keeps ``ray_tpu/_private/``
  free of Family-B findings.

See ``base.py`` for the rule model and ``PARITY.md`` ("Round-7") for the
rule catalog and suppression syntax (``# raytpu: ignore[RULE]``).
"""
from ray_tpu.lint import framework_rules, user_rules  # noqa: F401 (registry)
from ray_tpu.lint.base import (
    FAMILY_FRAMEWORK,
    FAMILY_USER,
    RULES,
    Finding,
    ModuleContext,
    Rule,
    lint_file,
    lint_paths,
    lint_source,
)
from ray_tpu.lint.decoration import (
    check_actor_class,
    check_remote_function,
    lint_enabled,
)

__all__ = [
    "FAMILY_FRAMEWORK",
    "FAMILY_USER",
    "RULES",
    "Finding",
    "ModuleContext",
    "Rule",
    "check_actor_class",
    "check_remote_function",
    "lint_enabled",
    "lint_file",
    "lint_paths",
    "lint_source",
]
