"""Generator for ``ray_tpu/lint/catalog.py`` (``--regen``).

The catalog is the single source of truth Family D lints against; this
module rebuilds its *derived* sections by scanning the tree:

* ``FAULTPOINTS`` names — literal first args of ``faultpoints.fire`` /
  ``async_fire`` calls under ``ray_tpu/`` (the lint package excluded);
  ``matrixed`` is True when the name appears in a chaos-spec string
  (``"point:kind:prob..."``) anywhere under ``tests/``.
* ``GATES`` — ``rt_config.declare(name, bool, True, ...)`` entries in
  ``_private/config.py`` (default-ON behavior gates).
* ``PHASES`` — the ``PHASES`` tuple in ``_private/taskpath.py``.
* ``STAGES`` — literal ``record_phase("<stage>", ...)`` /
  ``flight.record("task.<stage>", ...)`` first args.

Curated sections (``WIRE_FLAGS``, ``WIRE_BASE``, ``HEADER_VARS``,
``HEADER_KWARGS``, ``DYNAMIC_FIRE_PREFIXES``) and every ``waive`` reason
carry over from the existing catalog, so regenerating on a clean tree is
a byte-for-byte no-op (tests pin this) and a new fire site / gate /
phase shows up as a catalog diff the reviewer has to own.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

_CHAOS_RE = re.compile(r"^([a-z_.]+):(error|drop|delay|crash):")


def _repo_root() -> str:
    # ray_tpu/lint/catalog_gen.py -> repo root two levels above ray_tpu.
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _iter_sources(root: str) -> List[Tuple[str, ast.AST]]:
    out = []
    pkg = os.path.join(root, "ray_tpu")
    for dirp, dirs, files in os.walk(pkg):
        dirs[:] = sorted(d for d in dirs
                         if d not in ("__pycache__", "lint", ".git"))
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirp, name)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    tree = ast.parse(f.read(), path)
            except (SyntaxError, OSError):
                continue
            out.append((path, tree))
    return out


def scan_fire_names(root: str) -> List[str]:
    names = set()
    for _path, tree in _iter_sources(root):
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("fire", "async_fire")
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                names.add(node.args[0].value)
    return sorted(names)


def scan_matrixed(root: str) -> List[str]:
    """Faultpoint names referenced by chaos-spec strings under tests/."""
    names = set()
    tests = os.path.join(root, "tests")
    if not os.path.isdir(tests):
        return []
    for dirp, dirs, files in os.walk(tests):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            try:
                with open(os.path.join(dirp, name), encoding="utf-8") as f:
                    tree = ast.parse(f.read())
            except (SyntaxError, OSError):
                continue
            for node in ast.walk(tree):
                if (isinstance(node, ast.Constant)
                        and isinstance(node.value, str)):
                    m = _CHAOS_RE.match(node.value)
                    if m:
                        names.add(m.group(1))
    return sorted(names)


def scan_gates(root: str) -> List[str]:
    """Default-ON bool gates declared in _private/config.py."""
    path = os.path.join(root, "ray_tpu", "_private", "config.py")
    gates = []
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (SyntaxError, OSError):
        return gates
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "declare"
                and len(node.args) >= 3
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[1], ast.Name)
                and node.args[1].id == "bool"
                and isinstance(node.args[2], ast.Constant)
                and node.args[2].value is True):
            gates.append(node.args[0].value)
    return sorted(gates)


def scan_phases(root: str) -> Tuple[str, ...]:
    """The canonical PHASES tuple in _private/taskpath.py."""
    path = os.path.join(root, "ray_tpu", "_private", "taskpath.py")
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (SyntaxError, OSError):
        return ()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "PHASES":
                    if isinstance(node.value, ast.Tuple):
                        return tuple(
                            e.value for e in node.value.elts
                            if isinstance(e, ast.Constant)
                        )
    return ()


def scan_stages(root: str) -> List[str]:
    stages = set()
    for _path, tree in _iter_sources(root):
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if name == "record_phase":
                stages.add(node.args[0].value)
            elif name == "record" and node.args[0].value.startswith("task."):
                stages.add(node.args[0].value[len("task."):])
    return sorted(stages)


# ---------------------------------------------------------------- emission

def _emit_str_seq(name: str, values: Sequence[str], kind: str) -> List[str]:
    open_, close = (("(", ")") if kind == "tuple"
                    else ("frozenset({", "})"))
    lines = [f"{name} = {open_}"]
    for v in values:
        lines.append(f"    {v!r},")
    lines.append(f"{close}")
    return lines


def _emit_flag_map(name: str, data: Dict[str, dict]) -> List[str]:
    lines = [f"{name} = {{"]
    for key in sorted(data):
        entry = data[key]
        lines.append(f"    {key!r}: {{")
        for field in ("direction", "desc", "waive"):
            lines.append(f"        {field!r}: {entry.get(field)!r},")
        lines.append("    },")
    lines.append("}")
    return lines


def _emit_info_map(name: str, data: Dict[str, dict],
                   fields: Sequence[str]) -> List[str]:
    lines = [f"{name} = {{"]
    for key in sorted(data):
        entry = data[key]
        body = ", ".join(f"{f!r}: {entry.get(f)!r}" for f in fields)
        lines.append(f"    {key!r}: {{{body}}},")
    lines.append("}")
    return lines


_DOCSTRING = '''"""Pinned protocol/config/chaos/phase catalog (Family D source of truth).

GENERATED by ``python -m ray_tpu.lint --regen`` (see
``lint/catalog_gen.py``); regenerating on a clean tree is a no-op. Edit
by hand only to (a) curate ``WIRE_FLAGS`` / ``WIRE_BASE`` /
``HEADER_VARS`` entries for protocol changes, or (b) set a ``waive``
reason string — waived entries are exempt from the corresponding RT4xx
requirement but stay pinned here so the exemption is reviewable. Then
run ``--regen``: derived sections (faultpoints, gates, phases, stages)
rebuild from the tree and your curation carries over.

Consumed by ``lint/invariant_rules.py``:

* RT401 — every ``WIRE_FLAGS`` key needs a pack site AND a consume
  site; short header keys packed outside ``WIRE_FLAGS``/``WIRE_BASE``
  are uncataloged wire drift.
* RT402 — every ``GATES`` entry must be declared default-ON in
  ``rt_config`` and read somewhere with a reachable off-branch.
* RT403 — every literal ``faultpoints.fire`` name must appear in
  ``FAULTPOINTS`` and be chaos-matrixed or waived.
* RT404 — every ``record_phase`` stage / ``phase=`` label must appear
  in ``STAGES`` / ``PHASES``; ``PHASES`` must match
  ``taskpath.PHASES`` exactly.
"""'''


def generate(root: Optional[str] = None) -> str:
    """Render the full catalog.py source for ``root`` (repo root)."""
    root = root or _repo_root()
    try:
        from ray_tpu.lint import catalog as cur
    except ImportError:  # pragma: no cover - bootstrap only
        cur = None

    def curated(name, default):
        return getattr(cur, name, default) if cur is not None else default

    wire_flags = curated("WIRE_FLAGS", _SEED_WIRE_FLAGS)
    wire_base = curated("WIRE_BASE", _SEED_WIRE_BASE)
    header_vars = curated("HEADER_VARS", _SEED_HEADER_VARS)
    header_kwargs = curated("HEADER_KWARGS", _SEED_HEADER_KWARGS)
    dyn_prefixes = curated("DYNAMIC_FIRE_PREFIXES", _SEED_DYN_PREFIXES)
    old_fps = curated("FAULTPOINTS", _SEED_FAULTPOINT_WAIVES)
    old_gates = curated("GATES", {})

    matrixed = set(scan_matrixed(root))
    faultpoints = {}
    for name in scan_fire_names(root):
        prev = old_fps.get(name, {})
        faultpoints[name] = {
            "matrixed": name in matrixed if matrixed else
            bool(prev.get("matrixed")),
            "waive": prev.get("waive"),
        }
    gates = {
        name: {"waive": old_gates.get(name, {}).get("waive")}
        for name in scan_gates(root)
    }
    phases = scan_phases(root)
    stages = scan_stages(root)

    parts: List[str] = [_DOCSTRING, ""]
    parts.extend(_emit_str_seq("HEADER_VARS", tuple(header_vars), "tuple"))
    parts.append("")
    parts.extend(_emit_str_seq("HEADER_KWARGS", tuple(header_kwargs),
                               "tuple"))
    parts.append("")
    parts.extend(_emit_flag_map("WIRE_FLAGS", wire_flags))
    parts.append("")
    parts.extend(_emit_str_seq("WIRE_BASE", sorted(wire_base), "frozenset"))
    parts.append("")
    parts.extend(_emit_info_map("GATES", gates, ("waive",)))
    parts.append("")
    parts.extend(_emit_info_map("FAULTPOINTS", faultpoints,
                                ("matrixed", "waive")))
    parts.append("")
    parts.extend(_emit_str_seq("DYNAMIC_FIRE_PREFIXES",
                               tuple(dyn_prefixes), "tuple"))
    parts.append("")
    parts.extend(_emit_str_seq("PHASES", phases, "tuple"))
    parts.append("")
    parts.extend(_emit_str_seq("STAGES", tuple(stages), "tuple"))
    parts.append("")
    return "\n".join(parts)


def catalog_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "catalog.py")


def regen(root: Optional[str] = None, write: bool = True) -> bool:
    """Regenerate catalog.py. Returns True when the file changed."""
    text = generate(root)
    path = catalog_path()
    try:
        with open(path, encoding="utf-8") as f:
            old = f.read()
    except OSError:
        old = None
    if old == text:
        return False
    if write:
        with open(path, "w", encoding="utf-8") as f:
            f.write(text)
    return True


# ------------------------------------------------------------- bootstrap
# Seeds used only when catalog.py does not exist yet (first generation);
# afterwards the catalog itself is authoritative and these are inert.

_SEED_HEADER_VARS = ("h", "h2", "hdr", "header", "sub")
_SEED_HEADER_KWARGS = ("extras", "header")
_SEED_DYN_PREFIXES = ("gcs.dispatch.",)

_SEED_WIRE_FLAGS: Dict[str, dict] = {
    "sp": {
        "direction": "submitter -> executor",
        "desc": "pre-framed spec template: frame 0 is the interned spec "
                "blob, per-call header carries only deltas (SpecCache "
                "decodes each distinct blob once)",
        "waive": None,
    },
    "fb": {
        "direction": "submitter -> executor",
        "desc": "function-blob piggyback: the cloudpickle blob rides the "
                "first push_task carrying that fkey to each peer "
                "(FnPushLedger); the peer installs it without a kv_get",
        "waive": None,
    },
    "bh": {
        "direction": "executor -> submitter (reply)",
        "desc": "coalesced multi-result frame: list of sub-reply headers, "
                "each carrying its request's corr id under 'i' plus "
                "per-item rets/e/ec",
        "waive": None,
    },
    "bn": {
        "direction": "executor -> submitter (reply)",
        "desc": "frames-per-sub counts into the flat frame list "
                "(zipped with bh)",
        "waive": None,
    },
    "wa": {
        "direction": "executor -> submitter (reply, TCP only)",
        "desc": "window-ack request: the receiving pump answers a "
                "wa-tagged frame with a oneway mrack that clocks the "
                "sender's next ReplyWindow flush",
        "waive": None,
    },
    "an": {
        "direction": "submitter -> executor",
        "desc": "per-arg frame sections: intern-worthy args get their own "
                "serialized frames after the skeleton tuple; an lists "
                "each section's frame count",
        "waive": None,
    },
    "ai": {
        "direction": "submitter -> executor",
        "desc": "interned-arg references [[pos, digest]...]: these frames "
                "are OMITTED from the wire; the executor re-inserts exact "
                "bytes from its LRU or raises the typed arg_intern_miss",
        "waive": None,
    },
    "aib": {
        "direction": "submitter -> executor",
        "desc": "intern requests [[pos, digest]...] for frames PRESENT on "
                "this wire; the executor stores them under their digest "
                "for the pushes behind this one",
        "waive": None,
    },
    "_fr": {
        "direction": "transport -> consumer (in-band stamp)",
        "desc": "frame-arrival monotonic stamp set by the TCP recv loop / "
                "ring pump; pump-queue attribution and deadline re-arm "
                "read it (never serialized back out)",
        "waive": None,
    },
    "_tq": {
        "direction": "submitter (in-band stamp)",
        "desc": "queued-at stamp set at submission enqueue and popped "
                "before the wire; queue-wait attribution reads it",
        "waive": None,
    },
}

_SEED_WIRE_BASE = frozenset({
    "aid", "bm", "cg", "corr", "e", "ec", "fid", "fkey", "i", "m",
    "name", "nret", "oids", "r", "renv", "seq", "tid",
})

_SEED_FAULTPOINT_WAIVES: Dict[str, dict] = {
    "devstore.reshard": {"matrixed": False, "waive":
        "consumer-side reshard fallback after a sharding mismatch; "
        "exercised directly by tests/test_devstore.py unit specs"},
    "gcs.pubsub.publish": {"matrixed": False, "waive":
        "pubsub is best-effort with subscriber poll fallback; a matrix "
        "drop only slows convergence, asserted in targeted pubsub tests"},
    "protocol.rpc.read": {"matrixed": False, "waive":
        "reader-side corruption tears the connection down; ConnectionLost "
        "recovery is covered by transport unit tests, and a matrix drop "
        "here kills the whole pipe rather than one verb"},
    "ring.push": {"matrixed": False, "waive":
        "ring transport loss is matrixed end-to-end via "
        "worker.task.push/worker.reply.window deadline-replay specs; the "
        "raw ring point is exercised by tests/test_ring unit specs"},
    "ring.pop": {"matrixed": False, "waive":
        "see ring.push — pump-side loss rides the same deadline-replay "
        "matrix coverage; raw point exercised by ring unit tests"},
    "serve.proxy.route": {"matrixed": False, "waive":
        "serve chaos matrix injects at the replica boundary "
        "(serve.replica.call/stream); proxy route errors are asserted "
        "directly in tests/test_serve resilience cases"},
    "worker.dispatch.retry": {"matrixed": False, "waive":
        "the point exists to force the dispatch retry path "
        "deterministically in targeted tests; matrixing it would only "
        "re-test the retry loop the other specs already traverse"},
}
