"""LLM layer: serving + batch inference on the in-framework JAX engine.

Reference analog: ``python/ray/llm/`` (serve integration, vLLM engine
delegation, ``ray.data.llm`` batch processors).
"""
from ray_tpu.llm.batch import Processor, build_llm_processor
from ray_tpu.llm.config import ByteTokenizer, LLMConfig, load_tokenizer
from ray_tpu.llm.engine import DecodeEngine, SamplingParams
from ray_tpu.llm.serving import LLMServer, build_openai_app, serve_llm
from ray_tpu.llm.serving_patterns import (
    build_dp_openai_app,
    build_pd_openai_app,
)

__all__ = [
    "LLMConfig", "ByteTokenizer", "load_tokenizer",
    "DecodeEngine", "SamplingParams",
    "LLMServer", "build_openai_app", "serve_llm",
    "build_dp_openai_app", "build_pd_openai_app",
    "Processor", "build_llm_processor",
]
