"""LLM layer configuration.

Reference analog: ``python/ray/llm/_internal/common/models.py`` /
``serve/engines/vllm/vllm_models.py`` — ``LLMConfig`` carrying model id,
engine kwargs (tensor_parallel_size etc.), and serving knobs. The reference
delegates the engine to vLLM; here the engine is in-framework
(``ray_tpu/llm/engine.py`` — jitted JAX prefill/decode on the flagship
model), so engine kwargs map onto GPT2Config + mesh axes instead of vLLM
arguments.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Sequence


@dataclass
class LLMConfig:
    model_id: str = "gpt2-scratch"
    # Model: either explicit architecture numbers (fresh weights) or a path
    # to a pickled {"family": ..., "config": config kwargs, "params": pytree}
    # bundle ("family" defaults to gpt2 for old bundles).
    model_source: Optional[str] = None
    model_family: str = "gpt2"  # "gpt2" | "llama"
    vocab_size: int = 512
    max_seq_len: int = 1024
    num_layers: int = 4
    num_heads: int = 4
    num_kv_heads: Optional[int] = None  # llama GQA; None = num_heads (MHA)
    embed_dim: int = 256
    dtype: str = "bfloat16"
    # Mixture-of-Experts (Mixtral-style when model_family="llama"): number
    # of routed experts; 0 = dense. Decode routes each token through its
    # top-k experts (parallel/moe.py).
    moe_num_experts: int = 0
    moe_top_k: int = 2

    # Engine knobs (reference: engine_kwargs tensor_parallel_size etc.)
    max_batch_slots: int = 8
    prefill_buckets: Sequence[int] = (64, 128, 256)
    tensor_parallel_size: int = 1  # reserved: mesh "tensor" axis size
    # Prompt-lookup speculative decoding (vLLM spec-decode "[ngram]"
    # parity, TPU-first rationale: each verify step amortizes one program
    # dispatch over up to k tokens — dispatch latency dominates small-batch
    # decode through a tunneled/jitted path). OPT-IN; greedy requests only
    # (temperature 0 — rejection-sampling equivalence for stochastic
    # requests is out of scope and those requests fall back to 1-token
    # ticks). 0 disables; k = max draft tokens proposed per step.
    speculative_ngram_k: int = 0
    # Automatic prefix caching (vLLM-APC parity): completed prompt prefills
    # are kept in an LRU; identical prompts skip prefill entirely and
    # shared prefixes (system prompts) prefill only their tail. OPT-IN
    # (0 disables): each entry pins a full [L, 1, max_seq_len, ...] KV
    # pytree on device — size it against your HBM budget.
    prefix_cache_size: int = 0

    # Serving
    max_new_tokens_default: int = 64
    tokenizer: str = "byte"  # "byte" | local HF tokenizer dir

    accelerator_type: Optional[str] = None
    deployment_config: Dict[str, Any] = field(default_factory=dict)

    def model_config(self):
        import jax.numpy as jnp

        dtype = jnp.bfloat16 if self.dtype == "bfloat16" else jnp.float32
        moe = None
        if self.moe_num_experts:
            from ray_tpu.parallel.moe import MoEConfig

            moe = MoEConfig(
                num_experts=self.moe_num_experts,
                top_k=self.moe_top_k,
                activation=(
                    "swiglu" if self.model_family == "llama" else "gelu"
                ),
            )
        common = dict(
            vocab_size=self.vocab_size,
            max_seq_len=self.max_seq_len,
            num_layers=self.num_layers,
            num_heads=self.num_heads,
            embed_dim=self.embed_dim,
            dtype=dtype,
            attention_impl="xla",
            moe=moe,
        )
        if self.model_family == "llama":
            from ray_tpu.models.llama import LlamaConfig

            return LlamaConfig(
                num_kv_heads=self.num_kv_heads or self.num_heads, **common
            )
        if self.model_family == "gpt2":
            from ray_tpu.models.gpt2 import GPT2Config

            return GPT2Config(**common)
        raise ValueError(
            f"unknown model_family {self.model_family!r} (gpt2 | llama)"
        )

    def to_dict(self) -> dict:
        d = dict(self.__dict__)
        d["prefill_buckets"] = list(self.prefill_buckets)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "LLMConfig":
        return cls(**d)


class ByteTokenizer:
    """Self-contained UTF-8 byte tokenizer (ids = byte + 2; 0=pad, 1=eos).

    Stands in for a model tokenizer in environments with no downloadable
    vocab; real checkpoints bring their own tokenizer dir (``tokenizer``
    config field pointing at local HF files).
    """

    pad_id = 0
    eos_id = 1
    vocab_floor = 258

    def encode(self, text: str):
        return [b + 2 for b in text.encode("utf-8")]

    def decode(self, ids) -> str:
        # Total over any model vocab: ids beyond the byte range (the model
        # may have vocab_size > 258) decode to nothing rather than raising.
        return bytes(
            i - 2 for i in ids if 2 <= i <= 257
        ).decode("utf-8", errors="replace")


def load_tokenizer(config: LLMConfig):
    if config.tokenizer == "byte":
        return ByteTokenizer()
    from transformers import AutoTokenizer

    return AutoTokenizer.from_pretrained(config.tokenizer)
