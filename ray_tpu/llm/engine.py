"""JAX decode engine: slot-based continuous batching on static shapes.

Reference analog: the vLLM engine behind ``ray/llm`` serving
(``_internal/serve/engines/vllm/``) — continuous batching, prefill/decode
split, KV cache management. TPU-first redesign instead of a port:

- The KV cache is one static [L, B, S, H, D] pytree; every decode tick is a
  single compiled XLA program over ALL active slots (MXU-batched), not a
  per-request loop.
- Prompts prefill at bucketed lengths (few compile variants) into a
  batch=1 cache, then a jitted insert writes the slot row — requests join
  and leave the running batch without recompiling (the "continuous" part).
- Sampling happens host-side on the [B, V] logits of the tick (greedy /
  temperature / top-k), which keeps the compiled program sampling-agnostic.
"""
from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ray_tpu._private.backoff import Backoff
from ray_tpu.llm.config import LLMConfig, load_tokenizer


@dataclass
class SamplingParams:
    """Per-request sampling controls (reference: vLLM SamplingParams —
    the engine_kwargs surface ray/llm passes through)."""

    max_new_tokens: int = 64
    temperature: float = 0.0   # 0 = greedy
    top_k: int = 0             # 0 = no top-k cut
    top_p: float = 1.0         # nucleus: smallest set with cumprob >= top_p
    min_p: float = 0.0         # keep tokens with prob >= min_p * max_prob
    repetition_penalty: float = 1.0   # HF-style, over prompt + generated
    presence_penalty: float = 0.0     # flat penalty on seen generated ids
    frequency_penalty: float = 0.0    # per-count penalty on generated ids
    logprobs: int = 0          # >0: return chosen + top-N logprobs/token
    seed: Optional[int] = None  # per-request determinism
    stop_token_ids: Sequence[int] = field(default_factory=tuple)
    stop: Sequence[str] = field(default_factory=tuple)  # string stops


class GenerationResult(list):
    """Generated token ids; quacks as the plain list older callers expect,
    with per-token logprob entries riding along when requested."""

    def __init__(self, token_ids, logprobs=None):
        super().__init__(token_ids)
        self.logprobs = logprobs or []


@dataclass
class _Slot:
    active: bool = False
    token_ids: List[int] = field(default_factory=list)
    prompt_len: int = 0
    produced: int = 0
    params: SamplingParams = field(default_factory=SamplingParams)
    future: Optional[Future] = None
    last_token: int = 0
    length: int = 0  # current absolute position (== tokens in cache)
    prompt_ids: List[int] = field(default_factory=list)  # penalties
    logprobs: List[dict] = field(default_factory=list)
    rng: Optional[Any] = None  # per-request RandomState when seed given
    stream_q: Optional[Any] = None  # queue.Queue for token streaming


class DecodeEngine:
    def __init__(self, config: LLMConfig, params=None, seed: int = 0):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models import module_for

        self.config = config
        self.model_config = config.model_config()
        if params is None and config.model_source:
            import pickle

            with open(config.model_source, "rb") as f:
                bundle = pickle.load(f)
            params = jax.tree.map(jnp.asarray, bundle["params"])
            if "config" in bundle:
                # checkpoint architecture wins over LLMConfig defaults — a
                # mismatch would allocate a KV cache with the wrong layout
                family = bundle.get("family", self.config.model_family)
                if family == "llama":
                    from ray_tpu.models.llama import LlamaConfig

                    self.model_config = LlamaConfig(**bundle["config"])
                else:
                    from ray_tpu.models.gpt2 import GPT2Config

                    self.model_config = GPT2Config(**bundle["config"])
        if getattr(self.model_config, "moe", None) is not None:
            # Inference must route dropless: capacity-queue drops depend on
            # the rest of the batch, so prefill and per-step decode would
            # disagree (and with the full forward) on dropped tokens.
            import dataclasses

            self.model_config = dataclasses.replace(
                self.model_config,
                moe=dataclasses.replace(self.model_config.moe, dropless=True),
            )
        model = module_for(self.model_config)
        self.tokenizer = load_tokenizer(config)
        if params is None:
            params = model.init_params(
                self.model_config, jax.random.PRNGKey(seed)
            )
        self.params = params
        B, S = config.max_batch_slots, config.max_seq_len
        self._cache = model.init_kv_cache(self.model_config, B, S)
        self._rng = np.random.RandomState(seed)

        cfg = self.model_config

        def prefill(params, tokens, cache1, start):
            # start > 0 = continuation from a cached prefix: only the
            # prompt's tail runs through the model
            logits, cache1 = model.forward_cached(
                params, tokens, cache1, start, cfg
            )
            return logits, cache1

        def insert(batch_cache, slot_cache, b):
            return jax.tree.map(
                lambda c, s1: jax.lax.dynamic_update_slice(
                    c, s1.astype(c.dtype), (0, b, 0, 0, 0)
                ),
                batch_cache, slot_cache,
            )

        def decode(params, tokens, cache, lens):
            logits, cache = model.forward_cached(params, tokens, cache, lens, cfg)
            return logits[:, -1], cache

        def decode_all(params, tokens, cache, lens):
            # speculation verify: logits at EVERY position (position j's
            # row predicts the token after input j)
            logits, cache = model.forward_cached(
                params, tokens, cache, lens, cfg
            )
            return logits, cache

        self._prefill = jax.jit(prefill)
        self._insert = jax.jit(insert, donate_argnums=(0,))
        self._decode = jax.jit(decode, donate_argnums=(2,))
        self._spec_k = max(
            0, int(getattr(config, "speculative_ngram_k", 0) or 0)
        )  # negatives = disabled, never a half-armed dispatch path
        self._decode_spec = (
            jax.jit(decode_all, donate_argnums=(2,))
            if self._spec_k > 0 else None
        )
        self._empty_slot_cache = lambda: model.init_kv_cache(cfg, 1, S)

        self._slots = [_Slot() for _ in range(B)]
        self._pending: "queue.Queue" = queue.Queue()
        self._loop_thread: Optional[threading.Thread] = None
        self._stopped = False
        self._lock = threading.Lock()
        # Automatic prefix cache: prompt-token tuple -> {"cache": slot-cache
        # pytree (immutable jax arrays — safe to share), "logits_row":
        # final-position logits for per-request sampling}. LRU-bounded;
        # entries are whole completed prefills.
        from collections import OrderedDict

        self._prefix_cache: "OrderedDict[tuple, dict]" = OrderedDict()
        self.stats = {
            "requests": 0, "tokens_generated": 0, "ticks": 0,
            "prefix_hits": 0, "prefix_partial_hits": 0,
            "spec_proposed": 0, "spec_accepted": 0,
        }

    # ------------------------------------------------------------- sampling

    def _rng_for(self, p: SamplingParams):
        return (np.random.RandomState(p.seed) if p.seed is not None
                else self._rng)

    def _sample(self, logits_row: np.ndarray, p: SamplingParams,
                prompt_ids: Sequence[int] = (),
                generated: Sequence[int] = (), rng=None):
        """(next_token, logprob_entry|None). Penalties -> temperature ->
        logprobs snapshot -> top-k/top-p/min-p truncation -> draw (the
        reported distribution is pre-truncation, vLLM's convention)."""
        logits = logits_row.astype(np.float64, copy=True)
        if p.repetition_penalty != 1.0:
            union = set(prompt_ids) | set(generated)
            seen = np.fromiter(union, dtype=np.int64, count=len(union))
            if seen.size:
                vals = logits[seen]
                logits[seen] = np.where(
                    vals > 0, vals / p.repetition_penalty,
                    vals * p.repetition_penalty,
                )
        if (p.presence_penalty or p.frequency_penalty) and generated:
            ids, counts = np.unique(
                np.asarray(generated, np.int64), return_counts=True
            )
            logits[ids] -= (
                p.presence_penalty + p.frequency_penalty * counts
            )
        greedy = p.temperature <= 0
        if not greedy:
            logits = logits / max(p.temperature, 1e-5)
        lp_entry = None
        if p.logprobs > 0:
            shifted = logits - logits.max()
            logps = shifted - np.log(np.exp(shifted).sum())
            n = min(p.logprobs, logps.shape[0])
            top = np.argpartition(logps, -n)[-n:]
            top = top[np.argsort(logps[top])[::-1]]
            lp_entry = {
                "top": [(int(t), float(logps[t])) for t in top],
                "logps": logps,  # chosen-token logprob filled by caller
            }
        if greedy:
            nxt = int(np.argmax(logits))
        else:
            k = min(p.top_k, logits.shape[0])  # request-controlled: clamp
            if k > 0:
                kth = np.partition(logits, -k)[-k]
                logits = np.where(logits < kth, -np.inf, logits)
            shifted = logits - logits.max()
            probs = np.exp(shifted)
            probs /= probs.sum()
            if p.top_p < 1.0:
                order = np.argsort(probs)[::-1]
                cum = np.cumsum(probs[order])
                # smallest prefix reaching top_p (always keep the head)
                cut = int(np.searchsorted(cum, p.top_p)) + 1
                mask = np.zeros_like(probs, dtype=bool)
                mask[order[:cut]] = True
                probs = np.where(mask, probs, 0.0)
                probs /= probs.sum()
            if p.min_p > 0.0:
                keep = probs >= p.min_p * probs.max()
                probs = np.where(keep, probs, 0.0)
                probs /= probs.sum()
            nxt = int((rng or self._rng).choice(len(probs), p=probs))
        if lp_entry is not None:
            lp_entry = {
                "token": nxt,
                "logprob": float(lp_entry["logps"][nxt]),
                "top_logprobs": lp_entry["top"],
            }
        return nxt, lp_entry

    # ------------------------------------------------------------ lifecycle

    def _bucket(self, n: int) -> int:
        for b in self.config.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(
            f"prompt length {n} exceeds largest prefill bucket "
            f"{max(self.config.prefill_buckets)}"
        )

    def _prefix_lookup_locked(self, prompt_ids):
        """(entry, matched_len): exact entry, the longest cached strict
        prefix, or (None, 0)."""
        key = tuple(prompt_ids)
        entry = self._prefix_cache.get(key)
        if entry is not None:
            self._prefix_cache.move_to_end(key)
            return entry, len(prompt_ids)
        best, best_len, best_key = None, 0, None
        for k, e in self._prefix_cache.items():
            n = len(k)
            if best_len < n < len(prompt_ids) and key[:n] == k:
                best, best_len, best_key = e, n, k
        if best_key is not None:
            # a hot shared prefix must stay resident under LRU pressure
            self._prefix_cache.move_to_end(best_key)
        return best, best_len

    def _prefix_store_locked(self, prompt_ids, cache1, logits_np, base):
        """Store the full prompt AND its bucket-boundary prefixes (system
        prompts shared by many requests match through these). All entries
        alias the same immutable cache pytree; ``logits_np`` rows cover
        absolute positions base..base+rows-1."""
        cap = self.config.prefix_cache_size
        if cap <= 0:
            return
        n = len(prompt_ids)
        lengths = {n}
        for b in self.config.prefill_buckets:
            if base < b < n:
                lengths.add(b)
        for ln in lengths:
            row_idx = ln - base - 1
            if not (0 <= row_idx < logits_np.shape[0]):
                continue
            key = tuple(prompt_ids[:ln])
            self._prefix_cache[key] = {
                "cache": cache1,
                # copy: a view would pin the whole [Tpad, vocab] buffer
                "logits_row": logits_np[row_idx].copy(),
            }
            self._prefix_cache.move_to_end(key)
        while len(self._prefix_cache) > cap:
            self._prefix_cache.popitem(last=False)

    def _prefill_locked(self, prompt_ids, params, rng=None):
        """(slot_cache jax pytree, first_token, first_logprob). Caller
        holds the lock.
        Consults the prefix cache: an exact hit skips the model entirely; a
        strict-prefix hit prefills only the tail from the cached KV state."""
        import jax.numpy as jnp

        n = len(prompt_ids)
        self._bucket(n)  # uniform length limit: acceptance must not depend
        # on transient prefix-cache residency
        entry, matched = (
            self._prefix_lookup_locked(prompt_ids)
            if self.config.prefix_cache_size > 0
            else (None, 0)
        )
        if entry is not None and matched == n:
            self.stats["prefix_hits"] += 1
            first, lp = self._sample(
                entry["logits_row"], params, prompt_ids, (), rng
            )
            return entry["cache"], first, lp
        if entry is not None and (
            matched + self._bucket(n - matched) > self.config.max_seq_len
        ):
            # the padded tail write would clamp inside dynamic_update_slice
            # and corrupt valid prefix KV — full prefill instead
            entry, matched = None, 0
        if entry is not None:
            self.stats["prefix_partial_hits"] += 1
            base = matched
            rem = prompt_ids[matched:]
            Tpad = self._bucket(len(rem))
            toks = np.zeros((1, Tpad), np.int32)
            toks[0, : len(rem)] = rem
            logits, cache1 = self._prefill(
                self.params, jnp.asarray(toks), entry["cache"],
                jnp.full((1,), matched, jnp.int32),
            )
            logits_np = np.asarray(logits)[0]
            row = logits_np[len(rem) - 1]
        else:
            base = 0
            Tpad = self._bucket(n)
            toks = np.zeros((1, Tpad), np.int32)
            toks[0, :n] = prompt_ids
            logits, cache1 = self._prefill(
                self.params, jnp.asarray(toks), self._empty_slot_cache(),
                jnp.zeros((1,), jnp.int32),
            )
            logits_np = np.asarray(logits)[0]
            row = logits_np[n - 1]
        self._prefix_store_locked(prompt_ids, cache1, logits_np, base)
        first, lp = self._sample(row, params, prompt_ids, (), rng)
        return cache1, first, lp

    def _activate_slot_locked(self, b, cache1, first, prompt_len, params,
                              fut, prompt_ids=(), first_lp=None, rng=None):
        self._cache = self._insert(self._cache, cache1, b)
        slot = self._slots[b]
        slot.active = True
        slot.token_ids = [first]
        slot.prompt_len = prompt_len
        slot.params = params
        slot.produced = 1
        slot.future = fut
        slot.last_token = first
        slot.length = prompt_len
        slot.prompt_ids = list(prompt_ids)
        slot.logprobs = [first_lp] if first_lp is not None else []
        slot.rng = rng
        slot.stream_q = getattr(fut, "_rt_stream_q", None)
        if slot.stream_q is not None:
            slot.stream_q.put(first)
        self.stats["requests"] += 1
        self._finish_if_done_locked(b)

    def _admit_locked(self):
        import jax.numpy as jnp

        free = [i for i, s in enumerate(self._slots) if not s.active]
        while free and not self._pending.empty():
            try:
                item = self._pending.get_nowait()
            except queue.Empty:
                break
            b = free.pop(0)
            try:
                rng = None
                if item[0] == "prefilled":
                    # PD disaggregation: the prompt's KV was computed by a
                    # prefill server; insert its transferred cache directly.
                    _, prefilled, params, fut = item
                    cache1 = {
                        k: jnp.asarray(v)
                        for k, v in prefilled["cache"].items()
                    }
                    first = int(prefilled["first_token"])
                    prompt_len = int(prefilled["prompt_len"])
                    prompt_ids = tuple(prefilled.get("prompt_ids", ()))
                    first_lp = prefilled.get("first_logprob")
                    if params.seed is not None:
                        rng = self._rng_for(params)
                        if params.temperature > 0:
                            # the prefill server consumed one draw from
                            # this seed sampling the first token; skip it
                            # or token 2 reuses token 1's random value
                            rng.random_sample()
                else:
                    _, prompt_ids, params, fut = item
                    if params.seed is not None:
                        rng = self._rng_for(params)
                    cache1, first, first_lp = self._prefill_locked(
                        prompt_ids, params, rng
                    )
                    prompt_len = len(prompt_ids)
                if prompt_len <= 0:
                    raise ValueError("prompt must be non-empty")
                self._activate_slot_locked(
                    b, cache1, first, prompt_len, params, fut,
                    prompt_ids=prompt_ids, first_lp=first_lp, rng=rng,
                )
            except Exception as e:
                # Admission failure (bad bucket, mismatched transferred
                # cache shapes, ...) surfaces on the caller's future, never
                # on other slots or the scheduler loop.
                fut.set_exception(e)
                free.insert(0, b)
                continue

    def _finish_if_done_locked(self, b: int):
        slot = self._slots[b]
        stop = set(slot.params.stop_token_ids) | {self.tokenizer.eos_id}
        out = None
        done = (
            slot.produced >= slot.params.max_new_tokens
            or slot.last_token in stop
            or slot.length + 1 >= self.config.max_seq_len
        )
        if slot.params.stop:
            # Runs even when another criterion already fired: the final
            # token can both complete a stop needle and hit max_new_tokens,
            # and the needle must still be trimmed. String stops match on
            # the DECODED text (a stop may span token boundaries);
            # O(len^2) worst case over a request, bounded by
            # max_new_tokens.
            text = self.tokenizer.decode(slot.token_ids)
            for needle in slot.params.stop:
                idx = text.find(needle)
                if idx >= 0:
                    # trim to the tokens whose decode stays before the stop
                    keep = len(slot.token_ids)
                    while keep > 0 and len(
                        self.tokenizer.decode(slot.token_ids[:keep])
                    ) > idx:
                        keep -= 1
                    out = slot.token_ids[:keep]
                    done = True
                    break
        if done:
            if out is None:
                out = slot.token_ids
                if out and out[-1] in stop:
                    out = out[:-1]
            if slot.stream_q is not None:
                slot.stream_q.put(("__done__", len(out)))
            if slot.future is not None:
                slot.future.set_result(GenerationResult(
                    out, slot.logprobs[: len(out)]
                ))
            slot.active = False
            slot.future = None

    def _tick_locked(self) -> bool:
        if self._spec_k:
            return self._tick_spec_locked()
        return self._tick_plain_locked()

    # ------------------------------------------- prompt-lookup speculation

    def _propose_draft(self, slot, k: int):
        """Prompt-lookup proposal (vLLM "[ngram]" speculator): find the
        most recent earlier occurrence of the current 2-gram (then 1-gram)
        in prompt+generated history and copy its continuation."""
        hist = slot.prompt_ids + slot.token_ids
        # bounded lookback (vLLM [ngram] caps this too): an O(full-history)
        # scan per token would serialize long-context decode on the host
        window = 512
        if len(hist) > window:
            hist = hist[-window:]
        L = len(hist)
        for n in (2, 1):
            if L <= n:
                continue
            pat = hist[-n:]
            for i in range(L - n - 1, -1, -1):
                if hist[i:i + n] == pat:
                    # i <= L-n-1 guarantees a non-empty continuation
                    return hist[i + n:i + n + k]
        return []

    def _tick_spec_locked(self) -> bool:
        """Speculative tick: verify up to k drafted tokens per GREEDY slot
        in ONE dispatch (accepted prefix + one corrected token all come
        from the same logits). Stochastic slots ride along with draft
        length 0. Cache safety: forward_cached writes K/V before
        attending and masks keys beyond each query position, and later
        writes overwrite rejected-draft positions — stale KV can never
        be attended."""
        import jax.numpy as jnp

        active = [i for i, s in enumerate(self._slots) if s.active]
        if not active:
            return False
        K = self._spec_k
        S = self.config.max_seq_len
        if any(self._slots[i].length + 1 + K > S for i in active):
            # near the sequence end the [B, 1+K] write would CLAMP inside
            # dynamic_update_slice and overwrite valid KV — plain ticks
            # finish the tail
            return self._tick_plain_locked()
        drafts: Dict[int, list] = {}
        for i in active:
            slot = self._slots[i]
            if slot.params.temperature <= 0:
                d = self._propose_draft(slot, K)
                if d:
                    drafts[i] = d
                    self.stats["spec_proposed"] += len(d)
        if not drafts:
            # nothing to verify: the (1+K)-wide dispatch would pay ~K x
            # attention/logits cost for zero benefit
            return self._tick_plain_locked()
        B = len(self._slots)
        toks = np.zeros((B, 1 + K), np.int32)
        lens = np.zeros((B,), np.int32)
        for i in active:
            slot = self._slots[i]
            toks[i, :] = slot.last_token
            lens[i] = slot.length
            if i in drafts:
                d = drafts[i]
                toks[i, 1:1 + len(d)] = d
        logits, self._cache = self._decode_spec(
            self.params, jnp.asarray(toks), self._cache, jnp.asarray(lens)
        )
        logits = np.asarray(logits)
        for i in active:
            slot = self._slots[i]
            draft = drafts.get(i, [])
            for j in range(len(draft) + 1):
                nxt, lp = self._sample(
                    logits[i, j], slot.params, slot.prompt_ids,
                    slot.token_ids, slot.rng,
                )
                self._emit_token_locked(i, nxt, lp)
                if not slot.active:
                    break  # finished mid-run (stop/max/length)
                if j < len(draft):
                    if nxt != draft[j]:
                        break  # mismatch: later logits had wrong context
                    self.stats["spec_accepted"] += 1
        self.stats["ticks"] += 1
        return True

    def _emit_token_locked(self, i: int, nxt: int, lp) -> None:
        """Shared per-token bookkeeping for plain and speculative ticks."""
        slot = self._slots[i]
        slot.token_ids.append(nxt)
        if lp is not None:
            slot.logprobs.append(lp)
        if slot.stream_q is not None:
            slot.stream_q.put(nxt)
        slot.last_token = nxt
        slot.produced += 1
        slot.length += 1
        self.stats["tokens_generated"] += 1
        self._finish_if_done_locked(i)

    def _tick_plain_locked(self) -> bool:
        import jax.numpy as jnp

        active = [i for i, s in enumerate(self._slots) if s.active]
        if not active:
            return False
        B = len(self._slots)
        toks = np.zeros((B, 1), np.int32)
        lens = np.zeros((B,), np.int32)
        for i in active:
            toks[i, 0] = self._slots[i].last_token
            lens[i] = self._slots[i].length
        logits, self._cache = self._decode(
            self.params, jnp.asarray(toks), self._cache, jnp.asarray(lens)
        )
        logits = np.asarray(logits)
        for i in active:
            slot = self._slots[i]
            nxt, lp = self._sample(
                logits[i], slot.params, slot.prompt_ids, slot.token_ids,
                slot.rng,
            )
            self._emit_token_locked(i, nxt, lp)
        self.stats["ticks"] += 1
        return True

    # ------------------------------------------------------------- public

    def submit(self, prompt_ids: List[int],
               params: Optional[SamplingParams] = None) -> Future:
        """Continuous-batching entry: returns a Future of generated ids."""
        if not prompt_ids:
            raise ValueError("prompt must be non-empty")
        fut: Future = Future()
        self._pending.put(
            ("prompt", list(prompt_ids), params or SamplingParams(), fut)
        )
        self._ensure_loop()
        return fut

    def prefill_only(self, prompt_ids: List[int],
                     params: Optional[SamplingParams] = None) -> dict:
        """Prefill-server half of PD disaggregation (reference:
        ``serving_patterns/prefill_decode/builder.py``): compute the
        prompt's KV cache + first token WITHOUT occupying a decode slot.
        Returns a transferable dict a decode engine resumes from."""
        if not prompt_ids:
            raise ValueError("prompt must be non-empty")
        params = params or SamplingParams()
        with self._lock:
            cache1, first, lp = self._prefill_locked(
                list(prompt_ids), params, self._rng_for(params)
            )
            return {
                "cache": {k: np.asarray(v) for k, v in cache1.items()},
                "first_token": first,
                "prompt_len": len(prompt_ids),
                "first_logprob": lp,
                # penalties need the prompt on the DECODE side too
                "prompt_ids": list(prompt_ids),
            }

    def submit_prefilled(self, prefilled: dict,
                         params: Optional[SamplingParams] = None) -> Future:
        """Decode-server half of PD disaggregation: continue generation from
        a transferred prefill state."""
        fut: Future = Future()
        self._pending.put(
            ("prefilled", prefilled, params or SamplingParams(), fut)
        )
        self._ensure_loop()
        return fut

    def submit_stream(self, prompt_ids: List[int],
                      params: Optional[SamplingParams] = None):
        """Token-level streaming (reference: vLLM streaming generation /
        OpenAI stream=true). Yields generated token ids as the decode loop
        produces them; raises the request's error if admission fails.

        Stop-token trimming is reflected (the trimmed token is simply not
        yielded); string stops are NOT supported here — their trim point
        is only known at the end, so such requests must use submit()
        (the serving layer enforces this split)."""
        if params and params.stop:
            raise ValueError(
                "string stops are not streamable; use submit()"
            )
        import queue as _q

        fut: Future = Future()
        q: "_q.Queue" = _q.Queue()
        fut._rt_stream_q = q
        self._pending.put(
            ("prompt", list(prompt_ids), params or SamplingParams(), fut)
        )
        self._ensure_loop()

        def gen():
            while True:
                if fut.done() and fut.exception() is not None:
                    raise fut.exception()
                try:
                    item = q.get(timeout=1.0)
                except _q.Empty:
                    continue
                if isinstance(item, tuple) and item[0] == "__done__":
                    return
                # a stop TOKEN ends the request without being part of the
                # output; the done marker's kept-length already excludes
                # it, so check before yielding
                stop = set((params.stop_token_ids if params else ())
                           ) | {self.tokenizer.eos_id}
                if item in stop:
                    continue  # await the done marker
                yield item

        return gen()

    def generate(self, prompt_ids: List[int],
                 params: Optional[SamplingParams] = None) -> List[int]:
        """Synchronous single-request generation (batch path)."""
        return self.submit(prompt_ids, params).result(timeout=600)

    def generate_text(self, prompt: str,
                      params: Optional[SamplingParams] = None) -> str:
        ids = self.tokenizer.encode(prompt)
        out = self.generate(ids, params)
        return self.tokenizer.decode(out)

    def _ensure_loop(self):
        with self._lock:
            if self._loop_thread is not None and self._loop_thread.is_alive():
                return
            self._stopped = False
            self._loop_thread = threading.Thread(
                target=self._loop, daemon=True, name="rt-llm-engine"
            )
            self._loop_thread.start()

    def _loop(self):
        idle_since = None
        # Jittered tick: 2ms while work flows (reset below), backing
        # off to 20ms when idle so the park check isn't a busy spin.
        tick = Backoff(base=0.002, cap=0.02)
        while not self._stopped:
            try:
                with self._lock:
                    self._admit_locked()
                    busy = self._tick_locked()
            except Exception as e:
                # Never die holding unresolved futures: fail every in-flight
                # request, clear the slots, keep serving.
                with self._lock:
                    for slot in self._slots:
                        if slot.active and slot.future is not None:
                            slot.future.set_exception(e)
                        slot.active = False
                        slot.future = None
                busy = False
            if busy or not self._pending.empty():
                idle_since = None
                tick.reset()
                continue
            if idle_since is None:
                idle_since = time.monotonic()
            elif time.monotonic() - idle_since > 30:
                # Park. The pending re-check + handoff under the lock closes
                # the race with a submit() that saw this thread still alive.
                with self._lock:
                    if self._pending.empty():
                        self._loop_thread = None
                        return
                idle_since = None
            tick.sleep()

    def shutdown(self):
        self._stopped = True
        t = self._loop_thread
        if t is not None:
            t.join(timeout=5)
