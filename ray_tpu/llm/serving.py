"""OpenAI-compatible serving app over the serve layer.

Reference analog: ``ray.serve.llm build_openai_app`` / ``LLMServer``
(``python/ray/llm/_internal/serve/``): an ingress deployment exposing
/v1/completions and /v1/chat/completions, backed by engine replicas. Here
the engine is the in-framework JAX decode engine; TP passthrough maps to
engine mesh config rather than vLLM kwargs.
"""
from __future__ import annotations

import json
import time
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.llm.config import LLMConfig
from ray_tpu.llm.engine import DecodeEngine, SamplingParams


def extract_sampling(payload: dict, config: LLMConfig) -> SamplingParams:
    """OpenAI request fields → SamplingParams (shared by every ingress)."""
    stop = payload.get("stop") or ()
    if isinstance(stop, str):
        stop = (stop,)
    return SamplingParams(
        max_new_tokens=int(
            payload.get("max_tokens", config.max_new_tokens_default)
        ),
        temperature=float(payload.get("temperature", 0.0)),
        top_k=int(payload.get("top_k", 0)),
        top_p=float(payload.get("top_p", 1.0)),
        min_p=float(payload.get("min_p", 0.0)),
        repetition_penalty=float(payload.get("repetition_penalty", 1.0)),
        presence_penalty=float(payload.get("presence_penalty", 0.0)),
        frequency_penalty=float(payload.get("frequency_penalty", 0.0)),
        logprobs=int(payload.get("logprobs") or 0),
        seed=(int(payload["seed"]) if payload.get("seed") is not None
              else None),
        stop=tuple(stop),
    )


def _logprobs_block(completion_ids) -> dict:
    """OpenAI-style logprobs payload from a GenerationResult (token ids
    stand in for token strings — the engine's ids ARE its vocabulary)."""
    entries = getattr(completion_ids, "logprobs", None) or []
    return {
        "tokens": [e["token"] for e in entries],
        "token_logprobs": [e["logprob"] for e in entries],
        "top_logprobs": [
            {str(t): lp for t, lp in e["top_logprobs"]} for e in entries
        ],
    }


def completion_response(config: LLMConfig, prompt_tokens: int,
                        completion_ids, text: str, **extra) -> dict:
    """OpenAI text_completion envelope (shared by every ingress)."""
    choice = {"index": 0, "text": text, "finish_reason": "stop"}
    if getattr(completion_ids, "logprobs", None):
        choice["logprobs"] = _logprobs_block(completion_ids)
    return {
        "id": f"cmpl-{uuid.uuid4().hex[:24]}",
        "object": "text_completion",
        "created": int(time.time()),
        "model": config.model_id,
        "choices": [choice],
        "usage": {
            "prompt_tokens": prompt_tokens,
            "completion_tokens": len(completion_ids),
            "total_tokens": prompt_tokens + len(completion_ids),
        },
        **extra,
    }


class LLMServer:
    """Serve deployment target wrapping one engine replica."""

    def __init__(self, config_dict: dict, params=None):
        self.config = LLMConfig.from_dict(config_dict)
        self.engine = DecodeEngine(self.config, params=params)

    # serve ingress entry: HTTP payloads from the proxy, or direct dicts
    # from DeploymentHandle calls.
    def __call__(self, request: dict) -> dict:
        if "body" in request:  # HTTP proxy envelope
            path = request.get("path", "")
            try:
                payload = json.loads(request["body"] or b"{}")
            except json.JSONDecodeError:
                return {"error": {"message": "invalid JSON body"}}
            if payload.get("stream") and not payload.get("stop"):
                # OpenAI stream=true -> generator of SSE lines (the serve
                # replica registers it; the HTTP proxy forwards as SSE).
                # String stops need the full output for trimming, so they
                # fall through to the non-streaming path.
                chat = path.endswith("/chat/completions") or (
                    "messages" in payload
                )
                return self.completions_stream(payload, chat=chat)
            if path.endswith("/chat/completions"):
                return self.chat_completions(payload)
            return self.completions(payload)
        if request.get("stream") and not request.get("stop"):
            return self.completions_stream(
                request, chat="messages" in request
            )
        if "messages" in request:
            return self.chat_completions(request)
        return self.completions(request)

    # ----------------------------------------------------------- endpoints

    def _sampling(self, payload: dict) -> SamplingParams:
        return extract_sampling(payload, self.config)

    def completions(self, payload: dict) -> dict:
        prompt = payload.get("prompt", "")
        ids = self.engine.tokenizer.encode(prompt)
        out = self.engine.submit(ids, self._sampling(payload)).result(600)
        text = self.engine.tokenizer.decode(out)
        return completion_response(self.config, len(ids), out, text)

    def chat_completions(self, payload: dict) -> dict:
        prompt = self._chat_prompt(payload.get("messages", []))
        ids = self.engine.tokenizer.encode(prompt)
        out = self.engine.submit(ids, self._sampling(payload)).result(600)
        text = self.engine.tokenizer.decode(out)
        choice = {
            "index": 0,
            "message": {"role": "assistant", "content": text},
            "finish_reason": "stop",
        }
        if getattr(out, "logprobs", None):
            choice["logprobs"] = _logprobs_block(out)
        return {
            "id": f"chatcmpl-{uuid.uuid4().hex[:24]}",
            "object": "chat.completion",
            "created": int(time.time()),
            "model": self.config.model_id,
            "choices": [choice],
            "usage": {
                "prompt_tokens": len(ids),
                "completion_tokens": len(out),
                "total_tokens": len(ids) + len(out),
            },
        }

    def _chat_prompt(self, messages) -> str:
        return "".join(
            f"<{m.get('role', 'user')}>{m.get('content', '')}\n"
            for m in messages
        ) + "<assistant>"

    def completions_stream(self, payload: dict, *, chat: bool = False):
        """Generator of OpenAI SSE chunk lines (stream=true). Deltas are
        detokenized incrementally; the final line is ``data: [DONE]``
        (reference: ray.llm / vLLM streaming responses)."""
        if chat:
            prompt = self._chat_prompt(payload.get("messages", []))
        else:
            prompt = payload.get("prompt", "")
        ids = self.engine.tokenizer.encode(prompt)
        rid = (f"chatcmpl-{uuid.uuid4().hex[:24]}" if chat
               else f"cmpl-{uuid.uuid4().hex[:24]}")
        created = int(time.time())
        obj = "chat.completion.chunk" if chat else "text_completion"
        produced: List[int] = []
        prev_text = ""
        for tok in self.engine.submit_stream(ids, self._sampling(payload)):
            produced.append(tok)
            text = self.engine.tokenizer.decode(produced)
            # Hold back trailing replacement chars: a partial multi-byte
            # sequence decodes to U+FFFD that the next byte will fix —
            # emitting it would bake the wrong char into the stream.
            emit = text.rstrip("\ufffd")
            delta, prev_text = emit[len(prev_text):], emit
            if not delta:
                continue  # partial multi-byte/merge: hold until decodable
            if chat:
                choice = {"index": 0, "delta": {"content": delta},
                          "finish_reason": None}
            else:
                choice = {"index": 0, "text": delta, "finish_reason": None}
            yield "data: " + json.dumps({
                "id": rid, "object": obj, "created": created,
                "model": self.config.model_id, "choices": [choice],
            }) + "\n\n"
        # flush anything held back (a genuinely invalid trailing byte in
        # the final output emits as U+FFFD here, matching non-streaming)
        tail = self.engine.tokenizer.decode(produced)[len(prev_text):]
        if tail:
            tc = ({"index": 0, "delta": {"content": tail},
                   "finish_reason": None} if chat else
                  {"index": 0, "text": tail, "finish_reason": None})
            yield "data: " + json.dumps({
                "id": rid, "object": obj, "created": created,
                "model": self.config.model_id, "choices": [tc],
            }) + "\n\n"
        final = ({"index": 0, "delta": {}, "finish_reason": "stop"}
                 if chat else
                 {"index": 0, "text": "", "finish_reason": "stop"})
        yield "data: " + json.dumps({
            "id": rid, "object": obj, "created": created,
            "model": self.config.model_id, "choices": [final],
        }) + "\n\n"
        yield "data: [DONE]\n\n"

    def health_check(self) -> bool:
        return True


def build_openai_app(config: LLMConfig, *, num_replicas: int = 1,
                     params=None):
    """Application for ``serve.run(...)`` exposing the OpenAI surface at
    /v1 (reference: ``ray.serve.llm.build_openai_app``)."""
    from ray_tpu import serve

    deployment = serve.deployment(
        num_replicas=num_replicas,
        max_ongoing_requests=config.max_batch_slots,
        **config.deployment_config,
    )(LLMServer)
    return deployment.bind(config.to_dict(), params)


def serve_llm(config: LLMConfig, *, name: str = "llm", params=None,
              route_prefix: str = "/v1"):
    """Deploy and return (handle, app_name)."""
    from ray_tpu import serve

    app = build_openai_app(config, params=params)
    handle = serve.run(app, name=name, route_prefix=route_prefix)
    return handle
