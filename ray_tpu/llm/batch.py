"""Batch LLM inference over the data layer.

Reference analog: ``ray.data.llm`` processors
(``python/ray/llm/_internal/batch/processor/`` — vllm_engine_stage.py): a
configurable processor that maps a Dataset of prompts through an engine
stage with preprocess/postprocess hooks. Here the stage holds one JAX decode
engine per worker process and drives its continuous-batching queue with the
whole batch at once (slot-parallel decoding, not row-at-a-time).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.llm.config import LLMConfig
from ray_tpu.llm.engine import DecodeEngine, SamplingParams


class _EngineStage:
    """Callable applied via Dataset.map_batches; engine built lazily once
    per process and reused across batches."""

    _engine_cache: Dict[str, DecodeEngine] = {}

    def __init__(self, config_dict: dict, sampling: dict,
                 prompt_column: str, output_column: str):
        self.config_dict = config_dict
        self.sampling = sampling
        self.prompt_column = prompt_column
        self.output_column = output_column

    def _engine(self) -> DecodeEngine:
        key = repr(sorted(self.config_dict.items()))
        eng = self._engine_cache.get(key)
        if eng is None:
            eng = DecodeEngine(LLMConfig.from_dict(self.config_dict))
            self._engine_cache[key] = eng
        return eng

    def __call__(self, batch: Dict[str, list]) -> Dict[str, list]:
        eng = self._engine()
        params = SamplingParams(**self.sampling)
        prompts = batch[self.prompt_column]
        # Submit ALL rows first so the engine's slots fill (continuous
        # batching across the whole data batch), then collect in order.
        futs = [
            eng.submit(eng.tokenizer.encode(str(p)), params) for p in prompts
        ]
        outs = [eng.tokenizer.decode(f.result(600)) for f in futs]
        return {**batch, self.output_column: outs}


class Processor:
    def __init__(self, config: LLMConfig, *,
                 preprocess: Optional[Callable] = None,
                 postprocess: Optional[Callable] = None,
                 sampling: Optional[SamplingParams] = None,
                 prompt_column: str = "prompt",
                 output_column: str = "generated_text",
                 batch_size: int = 64):
        self._config = config
        self._pre = preprocess
        self._post = postprocess
        self._sampling = sampling or SamplingParams()
        self._prompt_column = prompt_column
        self._output_column = output_column
        self._batch_size = batch_size

    def __call__(self, dataset):
        if self._pre is not None:
            dataset = dataset.map(self._pre)
        # Class-based map_batches: the engine stage runs on a stateful actor
        # pool, so the engine is constructed once per actor and reused
        # across every block (reference: vllm_engine_stage on the actor-pool
        # map operator; per-task construction would pay model load + jit
        # compile per block).
        dataset = dataset.map_batches(
            _EngineStage,
            batch_size=self._batch_size,
            concurrency=1,
            fn_constructor_args=(
                self._config.to_dict(),
                dict(self._sampling.__dict__),
                self._prompt_column,
                self._output_column,
            ),
        )
        if self._post is not None:
            dataset = dataset.map(self._post)
        return dataset


def build_llm_processor(config: LLMConfig, **kwargs) -> Processor:
    """(reference: ``ray.data.llm.build_llm_processor``)"""
    return Processor(config, **kwargs)
