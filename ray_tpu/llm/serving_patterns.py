"""LLM serving patterns: data-parallel and prefill/decode disaggregation.

Reference analog: ``python/ray/llm/_internal/serve/serving_patterns/`` —
``data_parallel/dp_server.py:221`` (N identical engine replicas behind the
router) and ``prefill_decode/builder.py:184`` (separate prefill and decode
replica pools; the prompt's KV state transfers between them).

TPU-first shape: prefill is compute-bound (big matmuls, loves the MXU) and
decode is latency/HBM-bound — disaggregation sizes the two pools
independently. The transferred prefill state is a numpy KV pytree that rides
the zero-copy object path between replicas.
"""
from __future__ import annotations

import json
from typing import Optional

from ray_tpu.llm.config import LLMConfig
from ray_tpu.llm.engine import DecodeEngine, SamplingParams
from ray_tpu.llm.serving import (
    LLMServer,
    completion_response,
    extract_sampling,
)


def build_dp_openai_app(config: LLMConfig, *, num_replicas: int = 2,
                        params=None):
    """Data-parallel serving: N engine replicas, power-of-two routed
    (reference: dp_server.py)."""
    from ray_tpu.llm.serving import build_openai_app

    return build_openai_app(config, num_replicas=num_replicas, params=params)


class PrefillServer:
    """Prefill pool replica: prompts in, transferable KV states out."""

    def __init__(self, config_dict: dict, params=None):
        self.config = LLMConfig.from_dict(config_dict)
        self.engine = DecodeEngine(self.config, params=params)

    def prefill(self, prompt_ids, sampling: dict) -> dict:
        return self.engine.prefill_only(
            list(prompt_ids), SamplingParams(**sampling)
        )

    def health_check(self) -> bool:
        return True


class DecodeServer:
    """Decode pool replica: continues generation from transferred states."""

    def __init__(self, config_dict: dict, params=None):
        self.config = LLMConfig.from_dict(config_dict)
        self.engine = DecodeEngine(self.config, params=params)

    def decode(self, prefilled: dict, sampling: dict):
        return self.engine.submit_prefilled(
            prefilled, SamplingParams(**sampling)
        ).result(600)

    def health_check(self) -> bool:
        return True


class PDIngress:
    """OpenAI-surface ingress routing prompt->prefill pool->decode pool."""

    def __init__(self, config_dict: dict, prefill_handle, decode_handle):
        self.config = LLMConfig.from_dict(config_dict)
        from ray_tpu.llm.config import load_tokenizer

        self.tokenizer = load_tokenizer(self.config)
        self._prefill = prefill_handle
        self._decode = decode_handle

    def __call__(self, request: dict) -> dict:
        if "body" in request:  # HTTP proxy envelope
            try:
                payload = json.loads(request["body"] or b"{}")
            except json.JSONDecodeError:
                return {"error": {"message": "invalid JSON body"}}
        else:
            payload = request
        prompt = payload.get("prompt", "")
        sampling = dict(extract_sampling(payload, self.config).__dict__)
        ids = self.tokenizer.encode(prompt)
        if not ids:
            return {"error": {"message": "prompt must be non-empty"}}
        prefilled = self._prefill.prefill.remote(ids, sampling).result(600)
        out = self._decode.decode.remote(prefilled, sampling).result(600)
        text = self.tokenizer.decode(out)
        return completion_response(
            self.config, len(ids), out, text, disaggregated=True
        )

    def health_check(self) -> bool:
        return True


def build_pd_openai_app(config: LLMConfig, *, num_prefill: int = 1,
                        num_decode: int = 1, params=None):
    """Prefill/decode-disaggregated app for ``serve.run`` (reference:
    prefill_decode/builder.py:184). Weights must be shared: pass ``params``
    (or a config.model_source checkpoint) so both pools load identical
    models."""
    from ray_tpu import serve

    prefill_dep = serve.deployment(
        name="pd_prefill", num_replicas=num_prefill,
        max_ongoing_requests=config.max_batch_slots,
    )(PrefillServer)
    decode_dep = serve.deployment(
        name="pd_decode", num_replicas=num_decode,
        max_ongoing_requests=config.max_batch_slots,
    )(DecodeServer)
    ingress = serve.deployment(
        name="pd_ingress", max_ongoing_requests=64,
    )(PDIngress)
    cfg = config.to_dict()
    return ingress.bind(
        cfg,
        prefill_dep.bind(cfg, params),
        decode_dep.bind(cfg, params),
    )
