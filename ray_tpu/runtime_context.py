"""Runtime context (reference: ``python/ray/runtime_context.py``)."""
from __future__ import annotations

from dataclasses import dataclass

from ray_tpu._private.worker import get_global_worker


@dataclass
class RuntimeContext:
    job_id: str
    node_id: str
    worker_id: str
    is_driver: bool
    gcs_address: tuple

    def get_job_id(self) -> str:
        return self.job_id

    def get_node_id(self) -> str:
        return self.node_id

    def get_worker_id(self) -> str:
        return self.worker_id

    def get_task_id(self):
        from ray_tpu._private.worker import current_task_id_hex

        return current_task_id_hex()

    def get_actor_id(self):
        from ray_tpu._private.worker import current_actor_id_hex

        return current_actor_id_hex()


def get_runtime_context() -> RuntimeContext:
    w = get_global_worker()
    return RuntimeContext(
        job_id=w.job_id.hex(),
        node_id=w.node_id,
        worker_id=w.worker_id.hex(),
        is_driver=w.is_driver,
        gcs_address=w.gcs_addr,
    )
