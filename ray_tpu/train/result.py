"""Training result handed back from ``Trainer.fit`` (reference:
``python/ray/air/result.py`` Result)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint


@dataclass
class Result:
    metrics: Dict[str, Any] = field(default_factory=dict)
    # the trial's hyperparameters (reference: Result.config — how users
    # read the winning configuration off get_best_result())
    config: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    best_checkpoint: Optional[Checkpoint] = None
    path: Optional[str] = None
    error: Optional[str] = None
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
