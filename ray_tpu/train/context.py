"""Per-worker train context: ``report`` / ``get_checkpoint`` / ``get_context``.

Reference analog: ``python/ray/train/v2/api/train_fn_utils.py`` (report :23,
get_checkpoint :149, get_context :137) and the per-worker session plumbing
(``train/v2/_internal/execution/worker_group/thread_runner.py`` — the user
train_fn runs on a thread inside the worker actor; reports flow through a
queue the actor drains on ``poll``).

The context is thread-local: each train-worker actor runs its train_fn on a
dedicated thread, so multiple train workers co-located in one node process
never see each other's context.
"""
from __future__ import annotations

import os
import queue
import shutil
import threading
from typing import Any, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint

_tls = threading.local()


class TrainContext:
    """Visible to user code inside train_fn."""

    def __init__(
        self,
        world_rank: int,
        world_size: int,
        local_rank: int,
        local_world_size: int,
        node_rank: int,
        experiment_name: str,
        run_dir: str,
        latest_checkpoint: Optional[Checkpoint] = None,
        checkpoint_upload_rank: Optional[int] = 0,
        attempt: int = 0,
        run_nonce: str = "",
    ):
        self._attempt = attempt
        self._run_nonce = run_nonce
        self._world_rank = world_rank
        self._world_size = world_size
        self._local_rank = local_rank
        self._local_world_size = local_world_size
        self._node_rank = node_rank
        self._experiment_name = experiment_name
        self._run_dir = run_dir
        self._latest_checkpoint = latest_checkpoint
        self._checkpoint_upload_rank = checkpoint_upload_rank
        self._report_queue: "queue.Queue[dict]" = queue.Queue()
        self._report_seq = 0
        self.stop_event = threading.Event()

    # -- identity ----------------------------------------------------------
    def get_world_rank(self) -> int:
        return self._world_rank

    def get_world_size(self) -> int:
        return self._world_size

    def get_local_rank(self) -> int:
        return self._local_rank

    def get_local_world_size(self) -> int:
        return self._local_world_size

    def get_node_rank(self) -> int:
        return self._node_rank

    def get_experiment_name(self) -> str:
        return self._experiment_name

    def get_storage_path(self) -> str:
        return self._run_dir

    # -- report plumbing ---------------------------------------------------
    def _persist_checkpoint(self, checkpoint: Checkpoint, step_tag: str) -> str:
        """Copy the worker-local checkpoint dir into run storage.

        Storage is a path every host can see (local disk single-host, NFS /
        gcsfuse on a pod) — the TPU equivalent of the reference's fsspec
        upload (``train/_internal/storage.py``).
        """
        import uuid

        dest = os.path.join(self._run_dir, f"checkpoint_{step_tag}")
        if os.path.exists(dest):
            # Tag collision (controller re-run under the same RunConfig.name):
            # never alias to the stale directory — pick a unique one.
            dest = f"{dest}_{uuid.uuid4().hex[:6]}"
        tmp = dest + f".tmp{self._world_rank}"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        shutil.copytree(checkpoint.path, tmp)
        try:
            os.replace(tmp, dest)
        except OSError:
            # Must NOT return dest on failure — that would report a
            # checkpoint that was never persisted and corrupt later resumes.
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        return dest

    def report(self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
        persisted = None
        if checkpoint is not None and (
            self._checkpoint_upload_rank is None
            or self._world_rank == self._checkpoint_upload_rank
        ):
            persisted = self._persist_checkpoint(
                checkpoint, f"{self._attempt:03d}_{self._report_seq:06d}"
            )
        self._report_seq += 1
        self._report_queue.put(
            {
                "metrics": dict(metrics),
                "checkpoint_path": persisted,
                "rank": self._world_rank,
            }
        )

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self._latest_checkpoint

    def should_stop(self) -> bool:
        """Cooperative early-stop signal (elastic resize / shutdown)."""
        return self.stop_event.is_set()

    def drain_reports(self) -> list:
        out = []
        while True:
            try:
                out.append(self._report_queue.get_nowait())
            except queue.Empty:
                return out


def _set_context(ctx: Optional[TrainContext]):
    _tls.ctx = ctx


def get_context() -> TrainContext:
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        raise RuntimeError(
            "ray_tpu.train.get_context() called outside a train_fn"
        )
    return ctx


def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None):
    """Report metrics (+ optional checkpoint) from inside train_fn."""
    get_context().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    """Latest persisted checkpoint, for resume-after-failure."""
    return get_context().get_checkpoint()
