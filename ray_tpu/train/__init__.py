"""ray_tpu.train: the Train layer (reference: ``python/ray/train`` v2 API).

User surface::

    from ray_tpu import train
    from ray_tpu.train import JaxTrainer, ScalingConfig, RunConfig

    def train_fn(config):
        ctx = train.get_context()          # rank / world_size / ...
        ckpt = train.get_checkpoint()      # resume point after failure
        ...
        train.report({"loss": loss}, checkpoint=train.Checkpoint.from_directory(d))

    result = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=4, use_tpu=True),
        run_config=RunConfig(storage_path="/mnt/shared", name="run1"),
    ).fit()
"""
from ray_tpu.train.checkpoint import (
    Checkpoint,
    CheckpointManager,
    load_pytree,
    save_pytree,
)
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    JaxConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.context import get_checkpoint, get_context, report
from ray_tpu.train.controller import TrainController, TrainingFailedError
from ray_tpu.train.result import Result
from ray_tpu.train.step import (
    OptimizerConfig,
    create_train_state,
    make_eval_step,
    make_train_step,
)
from ray_tpu.train.trainer import (
    DataParallelTrainer,
    JaxTrainer,
    default_jax_train_loop,
    get_dataset_shard,
)

__all__ = [
    "Checkpoint",
    "CheckpointConfig",
    "CheckpointManager",
    "DataParallelTrainer",
    "FailureConfig",
    "JaxConfig",
    "JaxTrainer",
    "OptimizerConfig",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TrainController",
    "TrainingFailedError",
    "create_train_state",
    "default_jax_train_loop",
    "get_checkpoint",
    "get_context",
    "get_dataset_shard",
    "load_pytree",
    "make_eval_step",
    "make_train_step",
    "report",
    "save_pytree",
]
