"""TorchTrainer: torch.distributed data-parallel training on the framework.

Reference analog: ``python/ray/train/torch/`` — ``TorchConfig`` →
``_TorchBackend`` (config.py:256: sets MASTER_ADDR/PORT, calls
``dist.init_process_group``) and ``prepare_model`` / ``prepare_data_loader``
(``train/v2/torch/train_loop_utils.py``: DDP wrap + DistributedSampler).

On this framework torch is the CPU/host-side trainer family (gloo); the TPU
path is ``JaxTrainer``. Rendezvous rides the train control-plane collectives
(``broadcast_from_rank_zero``) instead of a backend-managed env handshake.
"""
from __future__ import annotations

import socket
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.trainer import DataParallelTrainer


@dataclass
class TorchConfig:
    backend: str = "gloo"
    init_timeout_s: float = 120.0
    env_vars: Dict[str, str] = field(default_factory=dict)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


# torch.distributed state is PER-PROCESS; two ranks co-hosted in one node
# process can never form a group. Guarded explicitly because the failure mode
# otherwise is a silent TCPStore hang. (Module-level helpers: the wrapped
# train_fn is cloudpickled, and a lock captured in its globals would not
# pickle — these resolve by module reference instead.)
import threading as _threading

_dist_lock = _threading.Lock()
_dist_owner: Optional[int] = None


def _acquire_dist_slot(rank: int):
    global _dist_owner
    with _dist_lock:
        if _dist_owner is not None:
            raise RuntimeError(
                "two train workers share one host process — "
                "torch.distributed can hold only one rank per process. "
                "Spread workers across hosts: "
                "ScalingConfig(placement_strategy='SPREAD') (the "
                "process-per-host model gives one worker per TPU/CPU host "
                "in real clusters)."
            )
        _dist_owner = rank


def _release_dist_slot(rank: int):
    global _dist_owner
    with _dist_lock:
        if _dist_owner == rank:
            _dist_owner = None


def _torch_wrapped(user_fn: Callable, torch_config: TorchConfig) -> Callable:
    def wrapped(config):
        import os

        import torch.distributed as dist

        from ray_tpu.train.collective import broadcast_from_rank_zero
        from ray_tpu.train.context import get_context

        ctx = get_context()
        world = ctx.get_world_size()
        inited = False
        if world > 1:
            # slot held from here; released in the finally below even when
            # rendezvous/init fails (a leak would poison this long-lived
            # host process for every later Torch run)
            from ray_tpu.train.torch import _acquire_dist_slot

            _acquire_dist_slot(ctx.get_world_rank())
        try:
            if world > 1:
                if ctx.get_world_rank() == 0:
                    # the address this worker's RPC server bound — routable
                    # by the cluster (loopback in local test clusters)
                    from ray_tpu._private.worker import get_global_worker

                    host = get_global_worker().addr[0]
                    master = (host, _free_port())
                else:
                    master = None
                master = broadcast_from_rank_zero(master, name="torch_master")
                os.environ.setdefault("MASTER_ADDR", master[0])
                os.environ.setdefault("MASTER_PORT", str(master[1]))
                if master[0].startswith("127."):
                    # single-machine rendezvous: gloo's interface
                    # autodetection hangs in hostname-less containers
                    os.environ.setdefault("GLOO_SOCKET_IFNAME", "lo")
                for k, v in torch_config.env_vars.items():
                    os.environ[k] = v
                dist.init_process_group(
                    backend=torch_config.backend,
                    init_method=f"tcp://{master[0]}:{master[1]}",
                    rank=ctx.get_world_rank(),
                    world_size=world,
                )
                inited = True
            takes_arg = True
            try:
                import inspect

                takes_arg = len(
                    inspect.signature(user_fn).parameters
                ) > 0
            except (TypeError, ValueError):
                pass
            return user_fn(config) if takes_arg else user_fn()
        finally:
            if inited:
                dist.destroy_process_group()
            if world > 1:
                from ray_tpu.train.torch import _release_dist_slot

                _release_dist_slot(ctx.get_world_rank())

    return wrapped


class TorchTrainer(DataParallelTrainer):
    """DDP trainer (reference: ``ray.train.torch.TorchTrainer``)."""

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        torch_config: Optional[TorchConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        super().__init__(
            _torch_wrapped(train_loop_per_worker,
                           torch_config or TorchConfig()),
            train_loop_config=train_loop_config,
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
        )


def prepare_model(model):
    """Wrap in DDP when distributed (reference:
    ``train_loop_utils.py prepare_model``); pass-through single-worker."""
    import torch.distributed as dist

    if dist.is_available() and dist.is_initialized():
        from torch.nn.parallel import DistributedDataParallel

        return DistributedDataParallel(model)
    return model


def prepare_data_loader(data_loader):
    """Re-build with a DistributedSampler when distributed (reference:
    ``prepare_data_loader``). Shuffling follows the original loader's
    sampler (a RandomSampler means the user asked for shuffle=True), and
    loader construction kwargs carry over."""
    import torch.distributed as dist

    if not (dist.is_available() and dist.is_initialized()):
        return data_loader
    from torch.utils.data import DataLoader, RandomSampler
    from torch.utils.data.distributed import DistributedSampler

    shuffle = isinstance(data_loader.sampler, RandomSampler)
    sampler = DistributedSampler(data_loader.dataset, shuffle=shuffle)
    kwargs = dict(
        batch_size=data_loader.batch_size,
        sampler=sampler,
        num_workers=data_loader.num_workers,
        pin_memory=data_loader.pin_memory,
        worker_init_fn=data_loader.worker_init_fn,
        collate_fn=data_loader.collate_fn,
        drop_last=data_loader.drop_last,
        timeout=data_loader.timeout,
        generator=data_loader.generator,
    )
    if data_loader.num_workers > 0:
        kwargs["persistent_workers"] = data_loader.persistent_workers
        kwargs["prefetch_factor"] = data_loader.prefetch_factor
    return DataLoader(data_loader.dataset, **kwargs)
