"""Torch-XLA backend: torch training on TPU via the XLA bridge.

Reference analog: ``python/ray/train/torch/xla/config.py`` —
``TorchXLAConfig`` (:20; the reference's is AWS-Neuron-only today): xrt/xla
env setup (:40-66) and ``dist.init_process_group("xla")`` (:68).

On this framework the first-class TPU path is ``JaxTrainer`` (XLA without
the torch bridge); this backend exists for torch-model parity when the
``torch_xla`` package is present in the worker image. It is import-gated:
constructing the trainer works anywhere (config validation is eager), and
the worker-side wrapper raises a clear error if ``torch_xla`` is missing
at run time rather than hanging in rendezvous.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.trainer import DataParallelTrainer


@dataclass
class TorchXLAConfig:
    # PJRT device the XLA bridge should target ("TPU"; "CPU" for tests
    # with a torch_xla CPU build).
    pjrt_device: str = "TPU"
    env_vars: Dict[str, str] = field(default_factory=dict)


def _xla_wrapped(user_fn: Callable, xla_config: TorchXLAConfig) -> Callable:
    def wrapped(config):
        import os

        try:
            import torch_xla  # noqa: F401
            import torch_xla.core.xla_model as xm  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "TorchXLATrainer needs the torch_xla package in the worker "
                "environment (runtime_env={'pip': ['torch_xla']} or a "
                "torch-xla image via image_uri). For TPU training without "
                "the torch bridge use JaxTrainer — the first-class path."
            ) from e

        from ray_tpu.train.context import get_context

        ctx = get_context()
        os.environ.setdefault("PJRT_DEVICE", xla_config.pjrt_device)
        for k, v in xla_config.env_vars.items():
            os.environ[k] = v
        world = ctx.get_world_size()
        inited = False
        if world > 1:
            import torch.distributed as dist

            from ray_tpu.train.collective import broadcast_from_rank_zero
            from ray_tpu.train.torch import _free_port

            if ctx.get_world_rank() == 0:
                from ray_tpu._private.worker import get_global_worker

                host = get_global_worker().addr[0]
                master = (host, _free_port())
            else:
                master = None
            master = broadcast_from_rank_zero(master, name="xla_master")
            os.environ.setdefault("MASTER_ADDR", master[0])
            os.environ.setdefault("MASTER_PORT", str(master[1]))
            # torch_xla >= 2.x registers the "xla" process-group backend on
            # import; rank/world ride the env like the reference's setup
            os.environ.setdefault("RANK", str(ctx.get_world_rank()))
            os.environ.setdefault("WORLD_SIZE", str(world))
            dist.init_process_group(
                backend="xla",
                rank=ctx.get_world_rank(),
                world_size=world,
            )
            inited = True
        try:
            takes_arg = True
            try:
                import inspect

                takes_arg = len(
                    inspect.signature(user_fn).parameters
                ) > 0
            except (TypeError, ValueError):
                pass
            return user_fn(config) if takes_arg else user_fn()
        finally:
            if inited:
                import torch.distributed as dist

                dist.destroy_process_group()

    return wrapped


class TorchXLATrainer(DataParallelTrainer):
    """Torch-on-TPU trainer via torch_xla (reference:
    ``ray.train.torch.xla.TorchXLAConfig`` + TorchTrainer)."""

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        torch_xla_config: Optional[TorchXLAConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        super().__init__(
            _xla_wrapped(train_loop_per_worker,
                         torch_xla_config or TorchXLAConfig()),
            train_loop_config=train_loop_config,
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
        )
