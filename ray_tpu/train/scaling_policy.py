"""Scaling policies: fixed and elastic world-size decisions.

Reference analogs: ``train/v2/_internal/execution/scaling_policy/fixed.py:13``
and ``elastic.py:29`` (decisions :165/:198). On TPU, elastic resize means
re-slicing: the new group re-initializes ``jax.distributed`` over the
surviving hosts and recompiles — so decisions are made only at (re)start
boundaries, not mid-run.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

from ray_tpu.train.config import ScalingConfig


@dataclass
class ScalingDecision:
    world_size: int


class ScalingPolicy:
    def __init__(self, scaling: ScalingConfig):
        self.scaling = scaling

    def initial_decision(self) -> ScalingDecision:
        raise NotImplementedError

    def recovery_decision(self) -> Optional[ScalingDecision]:
        """World size for a restart after failure; None = cannot restart."""
        raise NotImplementedError


class FixedScalingPolicy(ScalingPolicy):
    def initial_decision(self) -> ScalingDecision:
        return ScalingDecision(self.scaling.num_workers)

    def recovery_decision(self) -> Optional[ScalingDecision]:
        return ScalingDecision(self.scaling.num_workers)


class ElasticScalingPolicy(ScalingPolicy):
    """Restart with as many workers as currently fit in the cluster,
    clamped to [min_workers, num_workers]."""

    def _available_worlds(self) -> int:
        import ray_tpu

        per = self.scaling.worker_resources()
        avail = ray_tpu.available_resources()
        fits = math.inf
        for k, need in per.items():
            if need <= 0:
                continue
            fits = min(fits, avail.get(k, 0.0) / need)
        return int(fits) if fits is not math.inf else self.scaling.num_workers

    def initial_decision(self) -> ScalingDecision:
        n = min(self.scaling.num_workers, max(self._available_worlds(), 1))
        n = max(n, self.scaling.min_workers or 1)
        return ScalingDecision(n)

    def recovery_decision(self) -> Optional[ScalingDecision]:
        lo = self.scaling.min_workers or 1
        n = min(self.scaling.num_workers, self._available_worlds())
        if n < lo:
            return None
        return ScalingDecision(n)

    def grow_decision(self, current: int) -> Optional[ScalingDecision]:
        """Mid-run grow check (reference: elastic.py resize decisions —
        a returned node grows the world back toward num_workers). The
        running workers' resources are already acquired, so free capacity
        counts EXTRA worlds on top of ``current``."""
        if current >= self.scaling.num_workers:
            return None
        extra = self._available_worlds()
        n = min(self.scaling.num_workers, current + extra)
        if n > current:
            return ScalingDecision(n)
        return None


def make_scaling_policy(scaling: ScalingConfig) -> ScalingPolicy:
    return (
        ElasticScalingPolicy(scaling) if scaling.elastic
        else FixedScalingPolicy(scaling)
    )
