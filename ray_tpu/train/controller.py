"""Train controller: the control loop over the worker group.

Reference analog: ``train/v2/_internal/execution/controller/controller.py:103``
(``_run_control_loop_iteration`` :688, ``run`` :745). Differences, per
SURVEY.md §7: the loop runs in the driver process rather than a dedicated
controller actor — on a TPU pod the driver is itself a real host of the
slice (multi-controller JAX), so an extra actor hop buys nothing; the
controller-as-actor split can return when jobs outlive drivers.

Loop shape: scaling decision → start worker group → poll → (aggregate
reports, register rank-0 checkpoints) → on failure consult FailurePolicy +
ScalingPolicy and restart from the latest checkpoint → on completion return
:class:`Result`.
"""
from __future__ import annotations

import os
import time
from typing import Callable, Optional

from ray_tpu.train.checkpoint import CheckpointManager
from ray_tpu.train.config import JaxConfig, RunConfig, ScalingConfig
from ray_tpu.train.failure_policy import FailureDecision, FailurePolicy
from ray_tpu.train.result import Result
from ray_tpu.train.scaling_policy import make_scaling_policy
from ray_tpu.train.worker_group import WorkerGroup


class TrainingFailedError(RuntimeError):
    pass


class TrainController:
    def __init__(
        self,
        train_fn: Callable,
        train_loop_config: Optional[dict],
        scaling: ScalingConfig,
        run_config: RunConfig,
        jax_config: Optional[JaxConfig] = None,
        poll_interval: float = 0.05,
        recovery_timeout: float = 15.0,
    ):
        self._recovery_timeout = recovery_timeout
        self._train_fn = train_fn
        self._train_loop_config = train_loop_config
        self._scaling = scaling
        self._run_config = run_config
        self._jax_config = jax_config
        self._poll_interval = poll_interval

        name = run_config.name or f"train_{int(time.time())}"
        self._run_dir = os.path.join(run_config.resolved_storage_path(), name)
        self._ckpt_manager = CheckpointManager.restore_index(
            run_config.checkpoint_config, self._run_dir
        )
        self._failure_policy = FailurePolicy(run_config.failure_config)
        self._scaling_policy = make_scaling_policy(scaling)
        self._metrics_history: list = []
        self._latest_metrics: dict = {}

    def run(self) -> Result:
        decision = self._scaling_policy.initial_decision()
        world_size = decision.world_size
        attempt = 0
        # World size to revert to if a VOLUNTARY grow restart fails to
        # start (the capacity grow_decision saw raced away): not a training
        # failure, must not consume retries.
        grow_fallback = None
        while True:
            group = WorkerGroup(
                self._scaling,
                self._jax_config,
                os.path.basename(self._run_dir),
                self._run_dir,
            )
            try:
                group.start(
                    world_size,
                    self._train_fn,
                    self._train_loop_config,
                    self._ckpt_manager.latest_checkpoint,
                    attempt=attempt,
                )
            except Exception as e:  # start failed (e.g. resources not yet
                # released after a node death) — treat as a group failure,
                # unless this was a grow attempt: then just fall back.
                group.shutdown()
                if grow_fallback is not None:
                    world_size = grow_fallback
                    grow_fallback = None
                    attempt += 1
                    continue
                error = f"worker group start failed: {e}"
                if (
                    self._failure_policy.make_decision(error)
                    is FailureDecision.RAISE
                ):
                    raise TrainingFailedError(
                        f"training failed after "
                        f"{self._failure_policy.failures - 1} retries: "
                        f"{error}"
                    )
                world_size, attempt = self._await_recovery(error, attempt)
                continue
            grow_fallback = None
            try:
                error = self._monitor(group, world_size)
            except Exception as e:
                error = f"worker group poll failed: {e}"
            group.shutdown()
            if error is None:
                return Result(
                    metrics=self._latest_metrics,
                    checkpoint=self._ckpt_manager.latest_checkpoint,
                    best_checkpoint=self._ckpt_manager.best_checkpoint,
                    path=self._run_dir,
                    metrics_history=self._metrics_history,
                )
            if isinstance(error, tuple) and error[0] == "__grow__":
                # Capacity returned (elastic): resize up from the latest
                # checkpoint. Not a failure — does not consume retries
                # (reference: elastic.py resize decisions).
                grow_fallback = world_size
                world_size = error[1]
                attempt += 1
                continue
            if self._failure_policy.make_decision(error) is FailureDecision.RAISE:
                raise TrainingFailedError(
                    f"training failed after {self._failure_policy.failures - 1} "
                    f"retries: {error}"
                )
            world_size, attempt = self._await_recovery(error, attempt)

    def _await_recovery(self, error, attempt):
        """Wait for leases/health state to settle, then size the restart
        (resources of the failed group release asynchronously)."""
        recovery = None
        deadline = time.monotonic() + self._recovery_timeout
        while time.monotonic() < deadline:
            time.sleep(self._poll_interval * 4)
            recovery = self._scaling_policy.recovery_decision()
            if recovery is not None and recovery.world_size >= 1:
                break
        if recovery is None:
            raise TrainingFailedError(
                f"cannot restart: cluster below min_workers "
                f"({self._scaling.min_workers}); last error: {error}"
            )
        return recovery.world_size, attempt + 1

    def _monitor(self, group: WorkerGroup, world_size: int = 0):
        """Poll until all workers finish. Returns an error string, a
        ("__grow__", n) resize marker, or None."""
        grow_check = getattr(self._scaling_policy, "grow_decision", None)
        next_grow = time.monotonic() + 2.0
        while True:
            statuses = group.poll()
            error = None
            for st in statuses:
                for rep in st.reports:
                    self._ingest_report(rep)
                if st.error:
                    error = st.error
            if error:
                return error
            if all(st.done for st in statuses):
                return None
            # Elastic grow-back: when spare capacity appears mid-run and a
            # checkpoint exists to resume from, restart larger.
            if (
                grow_check is not None
                and time.monotonic() >= next_grow
                and self._ckpt_manager.latest_checkpoint is not None
            ):
                next_grow = time.monotonic() + 2.0
                decision = grow_check(world_size)
                if decision is not None:
                    return ("__grow__", decision.world_size)
            time.sleep(self._poll_interval)

    def _ingest_report(self, rep: dict):
        if rep["rank"] == 0:
            self._latest_metrics = rep["metrics"]
            self._metrics_history.append(rep["metrics"])
        if rep.get("checkpoint_path"):
            self._ckpt_manager.register(rep["checkpoint_path"], rep["metrics"])
