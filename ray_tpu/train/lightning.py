"""PyTorch Lightning glue: run Lightning loops inside TorchTrainer workers.

Reference analog: ``python/ray/train/lightning/`` — ``RayDDPStrategy``,
``RayLightningEnvironment`` (cluster env that reads ranks from the train
context instead of env-var guessing), ``RayTrainReportCallback`` (metrics →
``train.report``), and ``prepare_trainer`` (validates the strategy/env
combo).

Import-gated: lightning is not in the base image, so every entry point
raises a clear ImportError naming the runtime-env route when it is absent.
Use inside a ``TorchTrainer`` train loop — the process group is already
formed by the torch backend, so the strategy connects to it rather than
launching its own.
"""
from __future__ import annotations

from typing import Any


def _require_lightning():
    import importlib

    for root in ("pytorch_lightning", "lightning.pytorch"):
        try:
            pl = importlib.import_module(root)
        except ImportError:
            continue
        # Resolve the submodules we use explicitly: attribute access on a
        # package does not guarantee the submodule was imported.
        importlib.import_module(f"{root}.plugins.environments")
        importlib.import_module(f"{root}.strategies")
        return pl
    raise ImportError(
        "ray_tpu.train.lightning needs the 'pytorch_lightning' (or "
        "'lightning') package, which is not in this image. Provide "
        "it per-task: runtime_env={'pip': ['pytorch_lightning']} on "
        "the trainer's workers, or bake it into an image_uri "
        "environment."
    )


def RayLightningEnvironment():
    """Cluster environment mapping Lightning's rank/world queries onto the
    train context (reference: lightning/_lightning_utils.py)."""
    pl = _require_lightning()
    ClusterEnvironment = pl.plugins.environments.ClusterEnvironment

    from ray_tpu.train.context import get_context

    class _Env(ClusterEnvironment):
        @property
        def creates_processes_externally(self) -> bool:
            return True  # the worker group spawned us; Lightning must not

        @property
        def main_address(self) -> str:
            import os

            return os.environ.get("MASTER_ADDR", "127.0.0.1")

        @property
        def main_port(self) -> int:
            import os

            return int(os.environ.get("MASTER_PORT", "0"))

        @staticmethod
        def detect() -> bool:
            return True

        def world_size(self) -> int:
            return get_context().get_world_size()

        def set_world_size(self, size: int) -> None:
            pass

        def global_rank(self) -> int:
            return get_context().get_world_rank()

        def set_global_rank(self, rank: int) -> None:
            pass

        def local_rank(self) -> int:
            return get_context().get_local_rank()

        def node_rank(self) -> int:
            return get_context().get_node_rank()

    return _Env()


def RayDDPStrategy(**kwargs) -> Any:
    """DDP strategy that joins the worker group's existing process group
    (reference: lightning RayDDPStrategy)."""
    pl = _require_lightning()
    DDPStrategy = pl.strategies.DDPStrategy

    return DDPStrategy(
        cluster_environment=RayLightningEnvironment(), **kwargs
    )


def RayTrainReportCallback():
    """Per-epoch metrics → ``ray_tpu.train.report`` (reference:
    lightning RayTrainReportCallback)."""
    pl = _require_lightning()

    from ray_tpu.train.context import report

    class _Report(pl.Callback):
        def on_train_epoch_end(self, trainer, pl_module) -> None:
            metrics = {
                k: (v.item() if hasattr(v, "item") else v)
                for k, v in trainer.callback_metrics.items()
            }
            metrics["epoch"] = trainer.current_epoch
            metrics["step"] = trainer.global_step
            report(metrics)

    return _Report()


def prepare_trainer(trainer: Any) -> Any:
    """Validate a Lightning Trainer built for this worker group
    (reference: lightning/prepare_trainer)."""
    pl = _require_lightning()
    DDPStrategy = pl.strategies.DDPStrategy
    SingleDeviceStrategy = pl.strategies.SingleDeviceStrategy

    if not isinstance(
        trainer.strategy, (DDPStrategy, SingleDeviceStrategy)
    ):
        raise RuntimeError(
            "prepare_trainer: use RayDDPStrategy() (or single-device) so "
            "Lightning joins the worker group's process group instead of "
            "spawning its own"
        )
    return trainer
