"""User-facing trainers.

Reference analogs: ``python/ray/train/v2/api/data_parallel_trainer.py``
(``DataParallelTrainer``) and ``train/v2/jax/jax_trainer.py:20``
(``JaxTrainer`` — the SPMD/TPU trainer). ``JaxTrainer`` here goes further
than the reference: since the framework owns the model/step layer
(``ray_tpu.train.step``), it can run a complete sharded GPT-2 training loop
from config alone via :func:`default_jax_train_loop`.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.train.config import JaxConfig, RunConfig, ScalingConfig
from ray_tpu.train.controller import TrainController
from ray_tpu.train.result import Result


class DataParallelTrainer:
    """Runs ``train_loop_per_worker`` on a rank-ordered worker group."""

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        backend_config: Optional[JaxConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        self._train_loop = train_loop_per_worker
        self._train_loop_config = train_loop_config
        self._scaling_config = scaling_config or ScalingConfig()
        self._run_config = run_config or RunConfig()
        self._backend_config = backend_config
        self._datasets = datasets or {}

    def fit(self) -> Result:
        config = self._train_loop_config
        if self._datasets:
            # Dataset sharding (reference: train/_internal/data_config.py):
            # each worker iterates its rank's split via get_dataset_shard.
            config = dict(config or {})
            config["_datasets"] = self._datasets
        controller = TrainController(
            self._train_loop,
            config,
            self._scaling_config,
            self._run_config,
            self._backend_config,
        )
        return controller.run()


class JaxTrainer(DataParallelTrainer):
    """SPMD trainer for JAX on TPU (reference: ``jax_trainer.py:20``).

    Each worker is one JAX process (one TPU host). ``backend_config``
    controls platform selection and ``jax.distributed.initialize``.
    """

    def __init__(
        self,
        train_loop_per_worker: Optional[Callable] = None,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        jax_config: Optional[JaxConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        super().__init__(
            train_loop_per_worker or default_jax_train_loop,
            train_loop_config=train_loop_config,
            scaling_config=scaling_config,
            run_config=run_config,
            backend_config=jax_config or JaxConfig(),
            datasets=datasets,
        )


def default_jax_train_loop(config: Dict[str, Any]):
    """Complete sharded-GPT-2 training loop driven purely by config.

    config keys: ``model`` (GPT2Config kwargs), ``mesh`` (MeshConfig kwargs),
    ``optimizer`` (OptimizerConfig kwargs), ``num_steps``, ``batch_size``,
    ``seq_len``, ``checkpoint_every`` (0 = only at end), ``data_seed``.
    Reports ``{loss, step, tokens_per_sec}`` each step; saves orbax
    checkpoints; resumes from ``get_checkpoint()`` after failures.
    """
    import os
    import tempfile
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import get_preset
    from ray_tpu.parallel.mesh import MeshConfig
    from ray_tpu.train import checkpoint as ckpt_mod
    from ray_tpu.train.context import get_checkpoint, get_context, report
    from ray_tpu.train.step import (
        OptimizerConfig,
        create_train_state,
        make_train_step,
    )

    ctx = get_context()
    model = config.get("model", {})
    if isinstance(model, str):  # zoo preset, e.g. "gpt2-small" / "llama-1b"
        model_cfg = get_preset(model)
    else:
        model = dict(model)
        family = model.pop("family", "gpt2")
        for k in ("dtype", "param_dtype"):
            if isinstance(model.get(k), str):
                model[k] = jnp.dtype(model[k]).type
        if isinstance(model.get("moe"), dict):
            from ray_tpu.parallel.moe import MoEConfig

            model["moe"] = MoEConfig(**model["moe"])
        if family == "llama":
            from ray_tpu.models.llama import LlamaConfig

            model_cfg = LlamaConfig(**model)
        else:
            from ray_tpu.models.gpt2 import GPT2Config

            model_cfg = GPT2Config(**model)
    mesh = MeshConfig(**config.get("mesh", {"data": -1})).build()
    opt_cfg = OptimizerConfig(**config.get("optimizer", {}))
    opt = opt_cfg.build()
    num_steps = int(config.get("num_steps", 10))
    batch_size = int(config.get("batch_size", 8))
    seq_len = int(config.get("seq_len", model_cfg.max_seq_len))
    ckpt_every = int(config.get("checkpoint_every", 0))

    state = create_train_state(model_cfg, opt, jax.random.PRNGKey(0), mesh)
    start_step = 0
    prev = get_checkpoint()
    if prev is not None:
        with prev.as_directory() as d:
            state = ckpt_mod.load_pytree(d, target=state)
        start_step = int(state["step"])

    step_fn = make_train_step(model_cfg, opt, mesh)
    rng = np.random.default_rng(int(config.get("data_seed", 0)))

    from jax.sharding import NamedSharding, PartitionSpec as P

    batch_sharding = NamedSharding(mesh, P(("data", "fsdp"), None))

    def next_batch(step: int) -> dict:
        toks = rng.integers(
            0, model_cfg.vocab_size, (batch_size, seq_len + 1), dtype=np.int32
        )
        return jax.device_put({"tokens": toks}, {"tokens": batch_sharding})

    def save(state, step, metrics):
        if ctx.get_world_rank() != 0:
            return
        with tempfile.TemporaryDirectory(prefix="rt_local_ckpt_") as d:
            ckpt_mod.save_pytree(state, d)
            report(metrics, checkpoint=ckpt_mod.Checkpoint(d))

    t0 = time.monotonic()
    for step in range(start_step, num_steps):
        state, metrics = step_fn(state, next_batch(step))
        if ctx.should_stop():
            break
        loss = float(metrics["loss"])
        dt = max(time.monotonic() - t0, 1e-9)
        t0 = time.monotonic()
        m = {
            "loss": loss,
            "step": step + 1,
            "tokens_per_sec": batch_size * seq_len / dt,
        }
        is_ckpt_step = ckpt_every and (step + 1) % ckpt_every == 0
        if is_ckpt_step or step + 1 == num_steps:
            save(state, step + 1, m)
        else:
            report(m)
    return {"final_step": int(state["step"])}


def get_dataset_shard(name: str = "train"):
    """This rank's split of a dataset passed to the trainer (reference:
    ``ray.train.get_dataset_shard``)."""
    from ray_tpu.train.context import get_context

    ctx = get_context()
    ds = (getattr(ctx, "_datasets", None) or {}).get(name)
    if ds is None:
        return None
    rank, world = ctx.get_world_rank(), ctx.get_world_size()
    if hasattr(ds, "split"):  # ray_tpu.data.Dataset
        # equal shards: unequal row counts would desync SPMD step loops
        return ds.split(world, equal=True)[rank]
    if isinstance(ds, (list, tuple)):
        return list(ds[rank::world])
    return ds
