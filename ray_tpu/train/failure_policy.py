"""Failure policy: retry-or-raise after a worker-group failure.

Reference analog: ``train/v2/_internal/execution/failure_handling/`` —
``FailurePolicy.make_decision`` consuming ``FailureConfig.max_failures``.
"""
from __future__ import annotations

from enum import Enum

from ray_tpu.train.config import FailureConfig


class FailureDecision(Enum):
    RETRY = "retry"
    RAISE = "raise"


class FailurePolicy:
    def __init__(self, config: FailureConfig):
        self.config = config
        self.failures = 0

    def make_decision(self, error: str) -> FailureDecision:
        self.failures += 1
        if self.config.max_failures < 0:
            return FailureDecision.RETRY
        if self.failures <= self.config.max_failures:
            return FailureDecision.RETRY
        return FailureDecision.RAISE
