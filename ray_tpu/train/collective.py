"""Control-plane collectives among train workers.

Reference analog: ``python/ray/train/collective/collectives.py`` —
``broadcast_from_rank_zero`` (:16) and ``barrier`` (:59), used for
rendezvous-style coordination (master address exchange, phase sync) OUTSIDE
the data-plane collectives. Transport here is the head's KV (namespaced per
experiment + attempt + call sequence) — small control payloads only.
"""
from __future__ import annotations

import time
from typing import Any, Optional

import cloudpickle

from ray_tpu.train.context import get_context

_POLL_S = 0.02


def _seq(ctx, name: str) -> int:
    seqs = getattr(ctx, "_collective_seqs", None)
    if seqs is None:
        seqs = ctx._collective_seqs = {}
    n = seqs.get(name, 0)
    seqs[name] = n + 1
    return n


def namespace(experiment_name: str, run_nonce: str) -> str:
    """KV namespace for one worker-group start (shutdown reclaims it)."""
    return f"__train_collective:{experiment_name}:{run_nonce}:"


def _ns(ctx) -> str:
    # run_nonce is fresh per worker-group start: re-runs and elastic
    # restarts can never observe a previous group's rendezvous keys. The
    # attempt lives in the key prefix (one namespace per group start, so
    # shutdown can reclaim it wholesale).
    nonce = getattr(ctx, "_run_nonce", "")
    return namespace(ctx.get_experiment_name(), nonce)


def _key(ctx, rest: str) -> str:
    return f"{ctx._attempt}:{rest}"



def broadcast_from_rank_zero(data: Any = None, *, name: str = "bcast",
                             timeout_s: float = 60.0) -> Any:
    """Rank 0's ``data`` returned on every rank. All ranks must call in the
    same order (per-name call sequence keys the rendezvous)."""
    from ray_tpu._private.worker import get_global_worker

    ctx = get_context()
    w = get_global_worker()
    key = _key(ctx, f"{name}:{_seq(ctx, 'b:' + name)}")
    ns = _ns(ctx)
    if ctx.get_world_rank() == 0:
        w.run_sync(w.gcs.call(
            "kv_put", {"ns": ns, "key": key}, [cloudpickle.dumps(data)]
        ))
        return data
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        h, frames = w.run_sync(w.gcs.call("kv_get", {"ns": ns, "key": key}))
        if h.get("found"):
            return cloudpickle.loads(frames[0])
        time.sleep(_POLL_S)
    raise TimeoutError(f"broadcast_from_rank_zero({name}) timed out")


def allgather(data: Any = None, *, name: str = "allgather",
              timeout_s: float = 60.0) -> list:
    """Every rank's ``data``, rank-ordered, returned on every rank (used
    for rendezvous that needs all worker addresses, e.g. TF_CONFIG cluster
    specs). Same same-order contract as the other collectives."""
    from ray_tpu._private.worker import get_global_worker

    ctx = get_context()
    w = get_global_worker()
    gen = _seq(ctx, "g:" + name)
    ns = _ns(ctx)
    prefix = _key(ctx, f"ag:{name}:{gen}:")
    w.run_sync(w.gcs.call(
        "kv_put", {"ns": ns, "key": f"{prefix}{ctx.get_world_rank()}"},
        [cloudpickle.dumps(data)],
    ))
    deadline = time.monotonic() + timeout_s
    world = ctx.get_world_size()
    while time.monotonic() < deadline:
        h, _ = w.run_sync(w.gcs.call("kv_keys", {"ns": ns, "prefix": prefix}))
        if len(h.get("keys", [])) >= world:
            out = []
            for r in range(world):
                hh, frames = w.run_sync(w.gcs.call(
                    "kv_get", {"ns": ns, "key": f"{prefix}{r}"}
                ))
                if not hh.get("found"):
                    raise RuntimeError(f"allgather({name}): rank {r} vanished")
                out.append(cloudpickle.loads(frames[0]))
            return out
        time.sleep(_POLL_S)
    raise TimeoutError(f"allgather({name}) timed out")


def barrier(*, name: str = "barrier", timeout_s: float = 60.0):
    """Blocks until every rank of the group arrives (same-order contract)."""
    from ray_tpu._private.worker import get_global_worker

    ctx = get_context()
    w = get_global_worker()
    gen = _seq(ctx, "s:" + name)
    ns = _ns(ctx)
    prefix = _key(ctx, f"{name}:{gen}:")
    w.run_sync(w.gcs.call(
        "kv_put", {"ns": ns, "key": f"{prefix}{ctx.get_world_rank()}"}, [b""]
    ))
    deadline = time.monotonic() + timeout_s
    world = ctx.get_world_size()
    while time.monotonic() < deadline:
        h, _ = w.run_sync(w.gcs.call("kv_keys", {"ns": ns, "prefix": prefix}))
        if len(h.get("keys", [])) >= world:
            return
        time.sleep(_POLL_S)
    raise TimeoutError(f"barrier({name}) timed out")
