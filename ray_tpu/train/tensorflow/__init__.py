"""TensorflowTrainer: multi-worker TF training on the framework.

Reference analog: ``python/ray/train/tensorflow/`` — ``TensorflowConfig`` →
``_TensorflowBackend`` (``config.py``: ``_setup_tensorflow_environment``
builds the ``TF_CONFIG`` cluster spec from worker addresses and each
worker's rank) and ``prepare_dataset_shard``.

Rendezvous rides the train control-plane collectives (``allgather`` of
per-worker (host, port)) instead of the reference's backend-executor
address poll; the user loop then creates
``tf.distribute.MultiWorkerMirroredStrategy()``, which reads ``TF_CONFIG``.

On this framework TF is a CPU/host-side trainer family like torch-gloo;
the TPU path is ``JaxTrainer``.
"""
from __future__ import annotations

import socket
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.trainer import DataParallelTrainer


@dataclass
class TensorflowConfig:
    env_vars: Dict[str, str] = field(default_factory=dict)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


# A TF distributed runtime (server + collective ring) is per-process
# global, like torch.distributed: guard against two ranks sharing one
# host process (same rationale and failure mode — a silent rendezvous
# hang — as ray_tpu/train/torch's _dist_owner slot).
_slot_lock = threading.Lock()
_slot_owner: Optional[int] = None


def _acquire_tf_slot(rank: int):
    global _slot_owner
    with _slot_lock:
        if _slot_owner is not None:
            raise RuntimeError(
                "two train workers share one host process — TensorFlow's "
                "distributed runtime can hold only one rank per process. "
                "Spread workers across hosts: "
                "ScalingConfig(placement_strategy='SPREAD')."
            )
        _slot_owner = rank


def _release_tf_slot(rank: int):
    global _slot_owner
    with _slot_lock:
        if _slot_owner == rank:
            _slot_owner = None


def _tf_wrapped(user_fn: Callable, tf_config: TensorflowConfig) -> Callable:
    def wrapped(config):
        import json
        import os

        from ray_tpu.train.collective import allgather
        from ray_tpu.train.context import get_context

        ctx = get_context()
        world = ctx.get_world_size()
        rank = ctx.get_world_rank()
        if world > 1:
            from ray_tpu.train.tensorflow import (
                _acquire_tf_slot,
                _release_tf_slot,
            )

            _acquire_tf_slot(rank)
        try:
            if world > 1:
                from ray_tpu._private.worker import get_global_worker

                host = get_global_worker().addr[0]
                addrs = allgather(
                    f"{host}:{_free_port()}", name="tf_cluster"
                )
                os.environ["TF_CONFIG"] = json.dumps({
                    "cluster": {"worker": addrs},
                    "task": {"type": "worker", "index": rank},
                })
                for k, v in tf_config.env_vars.items():
                    os.environ[k] = v
            takes_arg = True
            try:
                import inspect

                takes_arg = len(
                    inspect.signature(user_fn).parameters
                ) > 0
            except (TypeError, ValueError):
                pass
            return user_fn(config) if takes_arg else user_fn()
        finally:
            if world > 1:
                os.environ.pop("TF_CONFIG", None)
                _release_tf_slot(rank)

    return wrapped


class TensorflowTrainer(DataParallelTrainer):
    """Multi-worker TF trainer (reference:
    ``ray.train.tensorflow.TensorflowTrainer``). Import of tensorflow is
    deferred to the workers: the driver never needs it."""

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        tensorflow_config: Optional[TensorflowConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        super().__init__(
            _tf_wrapped(train_loop_per_worker,
                        tensorflow_config or TensorflowConfig()),
            train_loop_config=train_loop_config,
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
        )


def prepare_dataset_shard(dataset):
    """Disable tf.data autosharding on an already-sharded dataset
    (reference: ``train/tensorflow/train_loop_utils.py
    prepare_dataset_shard`` — the framework shards via DataConfig, so
    tf.data must not shard again)."""
    import tensorflow as tf

    options = tf.data.Options()
    options.experimental_distribute.auto_shard_policy = (
        tf.data.experimental.AutoShardPolicy.OFF
    )
    return dataset.with_options(options)
