"""Train worker group: N actors each running the user train_fn on a thread.

Reference analog: ``python/ray/train/v2/_internal/execution/worker_group/
worker_group.py:88`` (``_start`` :194, ``poll_status`` :663) and
``thread_runner.py``. TPU-first notes: one worker per TPU *host* (process-
per-host is the JAX multi-controller model), ranks assigned deterministically
by (node, creation order) so rank 0 lands on the first host; the JAX backend
setup (env + ``jax.distributed.initialize``) mirrors ``train/v2/jax/
config.py:24`` ``_JaxBackend``.
"""
from __future__ import annotations

import logging
import os
import threading
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import JaxConfig, ScalingConfig
from ray_tpu.train.context import TrainContext, _set_context

logger = logging.getLogger(__name__)


class TrainWorker:
    """Actor hosting one train_fn run (one rank)."""

    def __init__(self):
        self._ctx: Optional[TrainContext] = None
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[str] = None
        self._done = False
        self._result: Any = None

    def setup(
        self,
        world_rank: int,
        world_size: int,
        local_rank: int,
        local_world_size: int,
        node_rank: int,
        experiment_name: str,
        run_dir: str,
        latest_checkpoint_path: Optional[str],
        env_vars: Dict[str, str],
        jax_distributed: Optional[dict] = None,
        attempt: int = 0,
        run_nonce: str = "",
    ) -> dict:
        for k, v in env_vars.items():
            os.environ[k] = v
        if jax_distributed:
            import jax

            jax.distributed.initialize(
                coordinator_address=jax_distributed["coordinator"],
                num_processes=jax_distributed["num_processes"],
                process_id=world_rank,
            )
        ckpt = (
            Checkpoint(latest_checkpoint_path) if latest_checkpoint_path else None
        )
        self._ctx = TrainContext(
            world_rank=world_rank,
            world_size=world_size,
            local_rank=local_rank,
            local_world_size=local_world_size,
            node_rank=node_rank,
            experiment_name=experiment_name,
            run_dir=run_dir,
            latest_checkpoint=ckpt,
            attempt=attempt,
            run_nonce=run_nonce,
        )
        import socket

        return {"hostname": socket.gethostname(), "pid": os.getpid()}

    def start(self, train_fn: Callable, train_loop_config: Optional[dict]) -> bool:
        assert self._ctx is not None, "setup() must run before start()"
        ctx = self._ctx
        if train_loop_config and "_datasets" in train_loop_config:
            train_loop_config = dict(train_loop_config)
            ctx._datasets = train_loop_config.pop("_datasets")

        def run():
            _set_context(ctx)
            try:
                takes_arg = True
                try:
                    import inspect

                    takes_arg = len(inspect.signature(train_fn).parameters) > 0
                except (TypeError, ValueError):
                    pass
                self._result = (
                    train_fn(train_loop_config or {}) if takes_arg else train_fn()
                )
            except BaseException as e:  # noqa: BLE001 — surfaced via poll()
                self._error = (
                    f"{type(e).__name__}: {e}\n{traceback.format_exc()}"
                )
            finally:
                self._done = True
                _set_context(None)

        self._thread = threading.Thread(target=run, name="rt-train-fn", daemon=True)
        self._thread.start()
        return True

    def poll(self) -> dict:
        # Capture done/error BEFORE draining: the train thread enqueues its
        # final report before setting _done (in its finally), so done=True
        # guarantees the drain below includes the last report. The reverse
        # order would let the final report slip between drain and the done
        # read and be lost forever.
        done = self._done
        error = self._error
        reports = self._ctx.drain_reports() if self._ctx else []
        return {"reports": reports, "done": done, "error": error}

    def request_stop(self) -> bool:
        if self._ctx:
            self._ctx.stop_event.set()
        return True

    def join(self, timeout: float = 10.0) -> dict:
        if self._thread:
            self._thread.join(timeout)
        return self.poll()

    def execute(self, fn: Callable, *args, **kwargs):
        """Run an arbitrary function in the worker process (reference:
        ``WorkerGroup.execute``)."""
        return fn(*args, **kwargs)

    def get_address(self) -> str:
        """Routable IP of this worker's host (for the jax.distributed
        coordinator, which must listen where other hosts can dial)."""
        import socket

        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.connect(("8.8.8.8", 80))  # no packets sent; picks the route
            ip = s.getsockname()[0]
            s.close()
            return ip
        except OSError:
            return socket.gethostbyname(socket.gethostname())


@dataclass
class WorkerStatus:
    reports: List[dict] = field(default_factory=list)
    done: bool = False
    error: Optional[str] = None
    dead: bool = False


class WorkerGroup:
    """Creates, polls, and tears down the rank-ordered actor group."""

    def __init__(
        self,
        scaling: ScalingConfig,
        jax_config: Optional[JaxConfig],
        experiment_name: str,
        run_dir: str,
    ):
        self._scaling = scaling
        self._jax = jax_config or JaxConfig()
        self._experiment_name = experiment_name
        self._run_dir = run_dir
        self.workers: List[Any] = []  # ActorHandles
        self.world_size = 0

    def start(
        self,
        world_size: int,
        train_fn: Callable,
        train_loop_config: Optional[dict],
        latest_checkpoint: Optional[Checkpoint],
        attempt: int = 0,
    ):
        import ray_tpu

        res = self._scaling.worker_resources()
        spread = self._scaling.placement_strategy in ("SPREAD", "STRICT_SPREAD")
        actor_cls = ray_tpu.remote(TrainWorker)
        opts: Dict[str, Any] = {
            "num_cpus": res.get("CPU", 1.0),
            "resources": {k: v for k, v in res.items() if k != "CPU"},
        }
        if spread:
            opts["scheduling_strategy"] = "SPREAD"
        # Append as we create so a mid-creation failure (e.g. unschedulable)
        # still leaves the partial group reachable for shutdown() to kill —
        # otherwise the created actors pin their resources forever.
        self.workers = []
        self.world_size = world_size
        for _ in range(world_size):
            self.workers.append(actor_cls.options(**opts).remote())

        env_vars = dict(self._jax.env_vars)
        if self._jax.jax_platforms:
            env_vars["JAX_PLATFORMS"] = self._jax.jax_platforms
        jax_dist = None
        if self._jax.distributed_init and world_size > 1:
            # The coordinator runs in rank 0's process; every host must dial
            # rank 0's routable address, not its own loopback (reference:
            # _JaxBackend + util/tpu.py:205 coordinator env construction).
            coord_host = self._jax.coordinator_address or ray_tpu.get(
                self.workers[0].get_address.remote(), timeout=60
            )
            jax_dist = {
                "coordinator": f"{coord_host}:{self._jax.coordinator_port}",
                "num_processes": world_size,
            }

        # Per-start nonce: scopes control-plane collectives so a re-run (or
        # elastic restart) can never read a previous group's rendezvous keys.
        import uuid as _uuid

        run_nonce = _uuid.uuid4().hex[:12]
        self._last_nonce = run_nonce
        # Deterministic ranks: worker i = rank i. Node-locality metadata from
        # setup() feeds local_rank; round-1 treats each worker as its own node
        # slot (process-per-host model).
        setups = [
            w.setup.remote(
                i,
                world_size,
                0,
                1,
                i,
                self._experiment_name,
                self._run_dir,
                latest_checkpoint.path if latest_checkpoint else None,
                env_vars,
                jax_dist,
                attempt,
                run_nonce,
            )
            for i, w in enumerate(self.workers)
        ]
        ray_tpu.get(setups, timeout=120)
        ray_tpu.get(
            [w.start.remote(train_fn, train_loop_config) for w in self.workers],
            timeout=120,
        )

    def poll(self, timeout: float = 30.0) -> List[WorkerStatus]:
        import ray_tpu

        statuses: List[WorkerStatus] = []
        for w in self.workers:
            # Any failure to reach a worker — actor death, node death, RPC
            # connection loss — is a worker failure the controller must see,
            # not an exception to propagate.
            try:
                h = ray_tpu.get(w.poll.remote(), timeout=timeout)
                statuses.append(
                    WorkerStatus(h["reports"], h["done"], h["error"], dead=False)
                )
            except Exception as e:  # noqa: BLE001
                statuses.append(
                    WorkerStatus([], True, f"worker unreachable: {e}", dead=True)
                )
        return statuses

    def shutdown(self, graceful_timeout: float = 5.0):
        import ray_tpu

        for w in self.workers:
            try:
                # Deliberate fire-and-forget: the worker is being killed
                # right after, so its stop-ack ref is never fetched.
                _ = w.request_stop.remote()
            except Exception:
                pass
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
        self.world_size = 0
        # reclaim this group's control-plane rendezvous keys
        nonce = getattr(self, "_last_nonce", None)
        if nonce:
            try:
                from ray_tpu._private.worker import get_global_worker

                from ray_tpu.train.collective import namespace

                w = get_global_worker()
                w.run_sync(w.gcs.call("kv_del_prefix", {
                    "ns": namespace(self._experiment_name, nonce),
                    "prefix": "",
                }))
            except Exception as e:
                # Cleanup of a finished experiment's rendezvous keys is
                # best-effort, but a dropped delete should be traceable.
                logger.debug("collective namespace cleanup failed: %s", e)
