"""Tree trainers: XGBoost / LightGBM on the framework's worker groups.

Reference analog: ``python/ray/train/xgboost/`` and
``python/ray/train/lightgbm/`` (v2 shape: a ``*Trainer`` running the
native library's distributed training inside the framework's worker group,
with the collective/rendezvous handled by the backend config —
``xgboost.collective`` rabit-style tracker / LightGBM machine lists).

Import-gated: the libraries are not in the base image, so constructing a
trainer raises a clear ImportError naming the runtime-env route instead of
failing deep inside a worker. When the library IS present, training runs:
single-worker fits natively; multi-worker wires the library's own
distributed setup from the train collectives (allgather of worker
addresses).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.trainer import DataParallelTrainer


def _require(module: str, trainer: str):
    import importlib

    try:
        return importlib.import_module(module)
    except ImportError as e:
        raise ImportError(
            f"{trainer} needs the '{module}' package, which is not in this "
            f"image. Provide it per-task: runtime_env={{'pip': "
            f"['{module}']}} on the trainer's workers, or bake it into an "
            f"image_uri environment."
        ) from e


def _xgb_loop(user_params: Dict[str, Any], label_column: str,
              num_boost_round: int) -> Callable:
    def loop(config):
        import numpy as np
        import xgboost as xgb

        from ray_tpu.train.collective import allgather
        from ray_tpu.train.context import get_context, report
        from ray_tpu.train.trainer import get_dataset_shard

        ctx = get_context()
        world = ctx.get_world_size()
        shard = get_dataset_shard("train")
        batches = list(shard.iter_batches(batch_size=65536))
        X = np.concatenate([
            np.stack([v for k, v in b.items() if k != label_column], 1)
            if len(b) > 2 else
            np.asarray(b[[k for k in b if k != label_column][0]]).reshape(
                len(b[label_column]), -1
            )
            for b in batches
        ])
        y = np.concatenate([np.asarray(b[label_column]) for b in batches])
        dtrain = xgb.DMatrix(X, label=y)
        if world > 1:
            # xgboost >= 2: native collective tracker. Rank 0 hosts it;
            # every rank joins via the gathered address.
            from xgboost import collective as xcoll
            from xgboost.tracker import RabitTracker

            from ray_tpu._private.worker import get_global_worker

            host = get_global_worker().addr[0]
            if ctx.get_world_rank() == 0:
                tracker = RabitTracker(
                    host_ip=host, n_workers=world, sortby="task"
                )
                tracker.start()
                args = tracker.worker_args()
            else:
                args = None
            args = allgather(args, name="xgb_tracker")[0]
            with xcoll.CommunicatorContext(**args):
                booster = xgb.train(
                    user_params, dtrain, num_boost_round=num_boost_round
                )
        else:
            booster = xgb.train(
                user_params, dtrain, num_boost_round=num_boost_round
            )
        if ctx.get_world_rank() == 0:
            report({"model_json": booster.save_raw("json").decode()})

    return loop


class XGBoostTrainer(DataParallelTrainer):
    """Distributed XGBoost (reference: ``ray.train.xgboost.XGBoostTrainer``).

    Gated: raises ImportError at construction when xgboost is absent."""

    def __init__(
        self,
        *,
        params: Dict[str, Any],
        label_column: str,
        num_boost_round: int = 10,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        _require("xgboost", "XGBoostTrainer")
        super().__init__(
            _xgb_loop(params, label_column, num_boost_round),
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
        )


def _lgbm_loop(user_params: Dict[str, Any], label_column: str,
               num_boost_round: int) -> Callable:
    def loop(config):
        import lightgbm as lgb
        import numpy as np

        from ray_tpu.train.collective import allgather
        from ray_tpu.train.context import get_context, report
        from ray_tpu.train.trainer import get_dataset_shard

        ctx = get_context()
        world = ctx.get_world_size()
        shard = get_dataset_shard("train")
        batches = list(shard.iter_batches(batch_size=65536))
        X = np.concatenate([
            np.stack([v for k, v in b.items() if k != label_column], 1)
            for b in batches
        ])
        y = np.concatenate([np.asarray(b[label_column]) for b in batches])
        params = dict(user_params)
        if world > 1:
            # LightGBM socket-mode distributed training: every machine
            # lists every (host, port); local rank picks its own port.
            import socket as _socket

            from ray_tpu._private.worker import get_global_worker

            host = get_global_worker().addr[0]
            with _socket.socket() as s:
                s.bind(("", 0))
                port = s.getsockname()[1]
            machines = allgather(f"{host}:{port}", name="lgbm_machines")
            params.update({
                "tree_learner": params.get("tree_learner", "data"),
                "num_machines": world,
                "machines": ",".join(machines),
                "local_listen_port": port,
            })
        train_set = lgb.Dataset(X, label=y)
        booster = lgb.train(params, train_set,
                            num_boost_round=num_boost_round)
        if ctx.get_world_rank() == 0:
            report({"model_str": booster.model_to_string()})

    return loop


class LightGBMTrainer(DataParallelTrainer):
    """Distributed LightGBM (reference:
    ``ray.train.lightgbm.LightGBMTrainer``). Gated like XGBoostTrainer."""

    def __init__(
        self,
        *,
        params: Dict[str, Any],
        label_column: str,
        num_boost_round: int = 10,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        _require("lightgbm", "LightGBMTrainer")
        super().__init__(
            _lgbm_loop(params, label_column, num_boost_round),
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
        )
