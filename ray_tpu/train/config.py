"""Train-layer config dataclasses.

Reference analogs: ``python/ray/air/config.py`` (ScalingConfig :inline,
RunConfig, FailureConfig, CheckpointConfig) and the JAX trainer's TPU
extensions (``python/ray/train/v2/jax/jax_trainer.py:57-64`` — ``use_tpu``,
``topology``, ``accelerator_type``). TPU-first differences: ``topology`` is a
typed field that resolves to a :class:`ray_tpu.parallel.mesh.TpuSliceSpec`,
and elasticity bounds live here (the reference splits them into
``ScalingPolicy`` constructor args).
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class ScalingConfig:
    """How many train workers to run and what each needs.

    ``num_workers`` is the target world size (one worker per TPU host in
    multi-host SPMD). ``min_workers`` < ``num_workers`` enables elastic
    training: on failure the group may restart smaller.
    """

    num_workers: int = 1
    use_tpu: bool = False
    topology: Optional[str] = None          # e.g. "2x2" / "4x4" (v5e chips)
    accelerator_type: Optional[str] = None  # e.g. "TPU-v5e"
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    min_workers: Optional[int] = None       # elastic lower bound

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", 1.0)
        if self.use_tpu and "TPU" not in res:
            res["TPU"] = 4.0  # chips per host, the v5e/v6e default
        return res

    @property
    def elastic(self) -> bool:
        return self.min_workers is not None and self.min_workers < self.num_workers


@dataclass
class FailureConfig:
    """How many worker-group failures to tolerate before giving up.

    ``max_failures=-1`` retries forever (reference semantics:
    ``air/config.py FailureConfig``).
    """

    max_failures: int = 0


@dataclass
class CheckpointConfig:
    """Top-K checkpoint retention (reference: ``air/config.py
    CheckpointConfig``; manager behavior ``train/v2/_internal/execution/
    checkpoint/checkpoint_manager.py:93``)."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"  # "max" | "min"

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")
        if self.num_to_keep is not None and self.num_to_keep <= 0:
            raise ValueError("num_to_keep must be positive or None")


@dataclass
class RunConfig:
    """Where results/checkpoints go and the failure/checkpoint policies."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)

    def resolved_storage_path(self) -> str:
        return os.path.expanduser(
            self.storage_path
            or os.environ.get("RAY_TPU_STORAGE_PATH", "~/ray_tpu_results")
        )


@dataclass
class JaxConfig:
    """Per-worker JAX process setup (reference: ``train/v2/jax/config.py:24``
    ``_JaxBackend`` — sets JAX_PLATFORMS + MEGASCALE coordinator env and calls
    ``jax.distributed.initialize``).

    On a real multi-host slice each train worker is one TPU host;
    ``distributed_init=True`` makes workers call
    ``jax.distributed.initialize(coordinator, num_processes, process_id)``.
    In single-host (and CPU-test) runs leave it False — the worker just sees
    its locally attached devices.
    """

    jax_platforms: Optional[str] = None
    distributed_init: bool = False
    coordinator_address: Optional[str] = None  # default: rank 0's host IP
    coordinator_port: int = 8476
    env_vars: Dict[str, str] = field(default_factory=dict)
