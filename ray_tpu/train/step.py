"""SPMD train step construction: state, shardings, jitted update.

This is the compute heart of the Train layer (reference analog: the user
train_fn a JaxTrainer runs, ``train/v2/jax/jax_trainer.py:20`` — except the
reference ships no model/step code; here the framework provides it).
Everything is one jit: forward+backward (remat'd), gradient psum over
data/fsdp (inserted by XLA from shardings), adamw update with sharded
optimizer state (ZeRO via the same param shardings).
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models import module_for
from ray_tpu.parallel.sharding import (
    DEFAULT_RULES,
    named_sharding,
    spec_from_logical,
)


@dataclass
class OptimizerConfig:
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    total_steps: int = 10000
    weight_decay: float = 0.1
    b1: float = 0.9
    b2: float = 0.95
    grad_clip: float = 1.0

    def build(self) -> optax.GradientTransformation:
        schedule = optax.warmup_cosine_decay_schedule(
            0.0, self.learning_rate, self.warmup_steps,
            max(self.total_steps, self.warmup_steps + 1),
        )
        return optax.chain(
            optax.clip_by_global_norm(self.grad_clip),
            optax.adamw(
                schedule, b1=self.b1, b2=self.b2,
                weight_decay=self.weight_decay,
            ),
        )


def param_shardings(mesh: Mesh, config, rules=None):
    """``config`` may be any model family's config (GPT2Config,
    LlamaConfig, ...); dispatch goes through ``models.module_for``."""
    axes = module_for(config).param_axes(config)
    return jax.tree.map(
        lambda a: named_sharding(mesh, a, rules),
        axes,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def create_train_state(
    config,
    opt: optax.GradientTransformation,
    key: jax.Array,
    mesh: Optional[Mesh] = None,
    rules=None,
) -> Dict[str, Any]:
    """Initialize {params, opt_state, step} directly sharded on the mesh
    (init under jit with out_shardings: no host-memory detour)."""
    model = module_for(config)
    if mesh is None:
        params = model.init_params(config, key)
        return {"params": params, "opt_state": opt.init(params), "step": 0}

    p_shard = param_shardings(mesh, config, rules)

    def init_fn(key):
        params = model.init_params(config, key)
        return params

    params = jax.jit(init_fn, out_shardings=p_shard)(key)

    # opt state shardings inferred by jit from the param shardings
    def opt_init(params):
        return opt.init(params)

    opt_state = jax.jit(opt_init)(params)
    step = jnp.zeros((), jnp.int32)
    return {"params": params, "opt_state": opt_state, "step": step}


def make_train_step(
    config,
    opt: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    rules=None,
    pipeline_microbatches: Optional[int] = None,
    donate: bool = True,
    seed: int = 0,
) -> Callable:
    """Build the jitted SPMD train step: (state, batch) → (state, metrics).

    ``rules`` override the logical-axis→mesh-axis sharding rules: when given
    (with a mesh), the step constrains params to those shardings so custom
    layouts are honored even if the input state arrived differently sharded.
    Stochastic layers (MoE router jitter) draw from a per-step key folded
    from ``seed`` and ``state["step"]``.
    """
    model = module_for(config)
    moe = getattr(config, "moe", None)
    needs_rng = moe is not None and moe.router_jitter > 0
    p_shard = (
        param_shardings(mesh, config, rules)
        if (mesh is not None and rules is not None)
        else None
    )

    def loss(params, batch, rng):
        return model.loss_fn(
            params, batch, config, mesh,
            pipeline_microbatches=pipeline_microbatches, rng=rng,
        )

    def step_fn(state, batch):
        params = state["params"]
        if p_shard is not None:
            params = jax.lax.with_sharding_constraint(params, p_shard)
        rng = (
            jax.random.fold_in(jax.random.PRNGKey(seed), state["step"])
            if needs_rng else None
        )
        (loss_val), grads = jax.value_and_grad(loss)(params, batch, rng)
        state = dict(state, params=params)
        updates, new_opt = opt.update(
            grads, state["opt_state"], state["params"]
        )
        new_params = optax.apply_updates(state["params"], updates)
        metrics = {
            "loss": loss_val,
            "grad_norm": optax.global_norm(grads),
            "step": state["step"] + 1,
        }
        return (
            {
                "params": new_params,
                "opt_state": new_opt,
                "step": state["step"] + 1,
            },
            metrics,
        )

    return jax.jit(step_fn, donate_argnums=(0,) if donate else ())


def make_eval_step(config, mesh=None) -> Callable:
    model = module_for(config)

    def eval_fn(params, batch):
        return model.loss_fn(params, batch, config, mesh)

    return jax.jit(eval_fn)
