"""HuggingFace Transformers integration for the Train layer.

Reference analog: ``python/ray/train/huggingface/transformers/`` —
``RayTrainReportCallback`` (HF Trainer callback that forwards logs and saved
checkpoints to ``ray.train.report``) and ``prepare_trainer``. Usage inside a
``TorchTrainer`` train_fn::

    from ray_tpu.train.huggingface import RayTrainReportCallback, prepare_trainer

    def train_fn(config):
        trainer = transformers.Trainer(model=..., args=..., ...)
        trainer = prepare_trainer(trainer)   # adds the report callback
        trainer.train()

Import-guarded: transformers is optional.
"""
from __future__ import annotations

import os
import tempfile
from typing import Optional

try:
    from transformers.trainer_callback import TrainerCallback
except ImportError:  # pragma: no cover - transformers always in test image
    TrainerCallback = object  # type: ignore[assignment,misc]


class RayTrainReportCallback(TrainerCallback):
    """Forward HF Trainer logs + checkpoints to ``ray_tpu.train.report``
    (reference: ``train/huggingface/transformers/_transformers_utils.py``
    RayTrainReportCallback). Metrics reported on every log; when the HF
    Trainer saves a checkpoint, the next report attaches it."""

    def __init__(self):
        self._pending_checkpoint: Optional[str] = None

    def on_save(self, args, state, control, **kwargs):
        ckpt_dir = os.path.join(
            args.output_dir, f"checkpoint-{state.global_step}"
        )
        if os.path.isdir(ckpt_dir):
            self._pending_checkpoint = ckpt_dir
        return control

    def on_log(self, args, state, control, logs=None, **kwargs):
        from ray_tpu.train import report
        from ray_tpu.train.checkpoint import Checkpoint

        metrics = dict(logs or {})
        metrics.setdefault("step", state.global_step)
        metrics.setdefault("epoch", state.epoch)
        ckpt = None
        if self._pending_checkpoint is not None:
            ckpt = Checkpoint(self._pending_checkpoint)
            self._pending_checkpoint = None
        report(metrics, checkpoint=ckpt)
        return control

    def on_train_end(self, args, state, control, **kwargs):
        # flush a trailing checkpoint that saved after the last log,
        # carrying the last logged metrics forward — this report becomes
        # the trial's last_result and must not erase e.g. "loss"
        if self._pending_checkpoint is not None:
            from ray_tpu.train import report
            from ray_tpu.train.checkpoint import Checkpoint

            metrics = {}
            for rec in state.log_history:
                metrics.update(rec)
            metrics.update({"step": state.global_step, "train_done": True})
            report(metrics, checkpoint=Checkpoint(self._pending_checkpoint))
            self._pending_checkpoint = None
        return control


def prepare_trainer(trainer):
    """Attach :class:`RayTrainReportCallback` to an HF Trainer if absent
    (reference: ``prepare_trainer``). Returns the trainer."""
    has = any(
        isinstance(cb, RayTrainReportCallback)
        for cb in getattr(trainer, "callback_handler").callbacks
    )
    if not has:
        trainer.add_callback(RayTrainReportCallback())
    return trainer
