"""Checkpoints: directory-based user checkpoints + top-K retention manager
+ orbax-backed jax pytree save/restore.

Reference analogs: ``python/ray/train/_checkpoint.py`` (Checkpoint = a
directory URI), ``train/v2/_internal/execution/checkpoint/
checkpoint_manager.py:93`` (top-K retention keyed on a score attribute),
``train/_internal/storage.py`` (fsspec/pyarrow storage paths — here local/
NFS/gcsfuse paths; TPU pods mount shared storage on every host).

TPU-first difference: the framework ships first-class jax state persistence
(:func:`save_pytree` / :func:`load_pytree` via orbax) because on TPU the
checkpointable state is a sharded pytree of ``jax.Array``; orbax handles
per-shard writes from each host in multi-controller SPMD.
"""
from __future__ import annotations

import json
import os
import shutil
import tempfile
import threading
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.train.config import CheckpointConfig


class Checkpoint:
    """A directory of state produced by (or handed to) a train_fn."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise ValueError(f"checkpoint path {path} is not a directory")
        return cls(path)

    def to_directory(self, dest: Optional[str] = None) -> str:
        """Copy checkpoint contents into ``dest`` (or a temp dir)."""
        dest = dest or tempfile.mkdtemp(prefix="rt_ckpt_")
        os.makedirs(dest, exist_ok=True)
        shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    @contextmanager
    def as_directory(self):
        """Read-only access to the checkpoint directory (no copy: storage is
        a host-visible filesystem path)."""
        yield self.path

    def __repr__(self):
        return f"Checkpoint({self.path})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))


@dataclass
class _Tracked:
    checkpoint: Checkpoint
    metrics: Dict[str, Any]
    index: int


@dataclass
class TrainingReport:
    """One ``report()`` call's payload as seen by the controller."""

    metrics: Dict[str, Any]
    checkpoint_path: Optional[str] = None
    rank: int = 0


class CheckpointManager:
    """Registers persisted checkpoints, retains top-K, deletes the rest."""

    def __init__(self, config: CheckpointConfig, run_dir: str):
        self._config = config
        self._run_dir = run_dir
        self._tracked: List[_Tracked] = []
        self._counter = 0
        self._lock = threading.Lock()
        os.makedirs(run_dir, exist_ok=True)

    @property
    def run_dir(self) -> str:
        return self._run_dir

    def register(self, path: str, metrics: Dict[str, Any]) -> Checkpoint:
        """Track a persisted checkpoint directory; evict beyond top-K."""
        ckpt = Checkpoint(path)
        with self._lock:
            self._tracked.append(_Tracked(ckpt, dict(metrics), self._counter))
            self._counter += 1
            self._evict_locked()
            self._write_index_locked()
        return ckpt

    def _score(self, t: _Tracked):
        attr = self._config.checkpoint_score_attribute
        if attr is None:
            return t.index  # recency
        v = t.metrics.get(attr)
        if v is None:
            return float("-inf") if self._config.checkpoint_score_order == "max" \
                else float("inf")
        return v

    def _evict_locked(self):
        k = self._config.num_to_keep
        if k is None or len(self._tracked) <= k:
            return
        reverse = self._config.checkpoint_score_order == "max"
        ranked = sorted(self._tracked, key=self._score, reverse=reverse)
        keep = ranked[:k]
        # never evict the most recent checkpoint — it's the resume point
        latest = max(self._tracked, key=lambda t: t.index)
        if latest not in keep:
            keep = keep[:-1] + [latest]
        for t in self._tracked:
            if t not in keep:
                shutil.rmtree(t.checkpoint.path, ignore_errors=True)
        self._tracked = [t for t in self._tracked if t in keep]

    def _write_index_locked(self):
        index = [
            {"path": t.checkpoint.path, "metrics": t.metrics, "index": t.index}
            for t in sorted(self._tracked, key=lambda t: t.index)
        ]
        tmp = os.path.join(self._run_dir, ".ckpt_index.tmp")
        with open(tmp, "w") as f:
            json.dump(index, f)
        os.replace(tmp, os.path.join(self._run_dir, "ckpt_index.json"))

    @property
    def latest_checkpoint(self) -> Optional[Checkpoint]:
        with self._lock:
            if not self._tracked:
                return None
            return max(self._tracked, key=lambda t: t.index).checkpoint

    @property
    def best_checkpoint(self) -> Optional[Checkpoint]:
        with self._lock:
            if not self._tracked:
                return None
            reverse = self._config.checkpoint_score_order == "max"
            return sorted(self._tracked, key=self._score, reverse=reverse)[0].checkpoint

    @property
    def checkpoints(self) -> List[Checkpoint]:
        with self._lock:
            return [t.checkpoint for t in sorted(self._tracked, key=lambda t: t.index)]

    @classmethod
    def restore_index(cls, config: CheckpointConfig, run_dir: str) -> "CheckpointManager":
        """Rebuild a manager from ``ckpt_index.json`` (controller restart)."""
        mgr = cls(config, run_dir)
        idx_path = os.path.join(run_dir, "ckpt_index.json")
        if os.path.exists(idx_path):
            with open(idx_path) as f:
                for entry in json.load(f):
                    if os.path.isdir(entry["path"]):
                        mgr._tracked.append(
                            _Tracked(Checkpoint(entry["path"]), entry["metrics"],
                                     entry["index"])
                        )
                        mgr._counter = max(mgr._counter, entry["index"] + 1)
        return mgr


# ---------------------------------------------------------------------------
# jax pytree persistence (orbax with a numpy fallback)
# ---------------------------------------------------------------------------

def save_pytree(state: Any, path: str) -> None:
    """Persist a pytree of arrays to ``path`` (a directory).

    Uses orbax (handles sharded ``jax.Array`` multi-host writes); falls back
    to a flat .npz + pickle treedef when orbax is unavailable.
    """
    os.makedirs(path, exist_ok=True)
    try:
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        target = os.path.join(os.path.abspath(path), "state")
        if os.path.exists(target):
            shutil.rmtree(target)
        ckptr.save(target, state)
        ckptr.wait_until_finished()
        ckptr.close()
        return
    except ImportError:
        pass
    import pickle

    import jax
    import numpy as np

    leaves, treedef = jax.tree.flatten(state)
    np.savez(os.path.join(path, "leaves.npz"),
             **{str(i): np.asarray(l) for i, l in enumerate(leaves)})
    with open(os.path.join(path, "treedef.pkl"), "wb") as f:
        pickle.dump(treedef, f)


def load_pytree(path: str, target: Any = None) -> Any:
    """Restore a pytree saved by :func:`save_pytree`.

    ``target`` (an abstract or concrete pytree of the same structure) guides
    orbax restoration — pass the freshly-initialized sharded state to restore
    directly onto the right devices/shardings.
    """
    orbax_dir = os.path.join(os.path.abspath(path), "state")
    if os.path.isdir(orbax_dir):
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        if target is not None:
            import jax

            abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, target)
            out = ckptr.restore(orbax_dir, abstract)
        else:
            out = ckptr.restore(orbax_dir)
        ckptr.close()
        return out
    import pickle

    import numpy as np

    with open(os.path.join(path, "treedef.pkl"), "rb") as f:
        treedef = pickle.load(f)
    data = np.load(os.path.join(path, "leaves.npz"))
    leaves = [data[str(i)] for i in range(len(data.files))]
    import jax

    return jax.tree.unflatten(treedef, leaves)
